"""Cluster duplication: ship a partition's committed mutations to a
follower cluster's table over the network, through the follower's 2PC.

Parity: the replica-side duplication pipeline (replica_duplicator.h:79,
duplication_pipeline.h:42-76) with pegasus_mutation_duplicator.h:56 as
the shipping backend — here the backend is the wire: shipped writes are
OP_DUP_PUT / OP_DUP_REMOVE mutations sent to the follower partition's
primary, which replicates them to the follower's members and resolves
conflicts via the carried source timetags.

WAN shape (Taurus, PAPERS.md: log shipping must be batched and
flow-controlled to survive real links): each tick loads a WINDOW of
committed mutations (`[pegasus.dup] ship_batch_mutations` /
`ship_batch_bytes`, budget-capped by the node's DupGovernor) and ships
each follower partition ONE `dup_apply_batch` envelope whose ops payload
is zstd-compressed with the block-codec machinery. The follower applies
an envelope's ops in decree order as one 2PC mutation and acks at the
batch's max decree; the ack carries the follower's foreground-pressure
counters back for the governor's AIMD backoff. Setting
ship_batch_mutations <= 1 degrades to the original solo-mutation
client_write shipping (the bench baseline).

Confirmation discipline (the part the in-process TableShipper doesn't
need): `confirmed_decree` advances ONLY after every follower partition
acks its envelope — a crash between ship and ack re-ships the same
window, which is safe because dup application is idempotent (same
timetag loses the `>` comparison the second time).
"""

from __future__ import annotations

import itertools
import struct
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.base.value_schema import (
    PEGASUS_EPOCH_BEGIN,
    expire_ts_from_ttl,
    generate_timetag,
)
from pegasus_tpu.replica.mutation import ATOMIC_OPS, Mutation
from pegasus_tpu.rpc.codec import (
    OP_DUP_PUT,
    OP_DUP_REMOVE,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
    encode_write,
)
from pegasus_tpu.utils.flags import FLAGS, define_flag
from pegasus_tpu.utils.metrics import METRICS

define_flag("pegasus.dup", "ship_batch_mutations", 32,
            "committed mutations one dup tick loads into a ship window "
            "(<=1 degrades to the legacy solo-mutation client_write "
            "shipping — one uncompressed mutation per round trip)",
            mutable=True)
define_flag("pegasus.dup", "ship_batch_bytes", 1 << 20,
            "log-byte cap on one ship window (the window always carries "
            "at least one mutation — forward-progress floor)",
            mutable=True)

_RIDS = itertools.count(1_000_000)
_LEN = struct.Struct("<I")

# fail_mode "skip": rejections of the same decree tolerated before the
# mutation is abandoned (each retry is a full re-resolve + re-ship round)
_FAIL_SKIP_RETRIES = 3

# structured rate-limited failure logging (PR 9 transport hygiene): a
# wedged follower must produce one countable line per interval per
# site, never silence — and operator-sanctioned loss (fail_mode=skip
# abandoning a decree) must be loudly visible
from pegasus_tpu.rpc.transport import _RateLimitedLog  # noqa: E402

_DUP_LOG = _RateLimitedLog()


class _DupError(RuntimeError):
    """Structured carrier for _DUP_LOG (it logs exception type + msg)."""


class ClusterDuplicator:
    """One partition's dup session on its primary's node.

    Driven by the stub: `tick()` from the dup timer; `on_write_reply` /
    `on_follower_config` from inbound messages. At most one WINDOW is in
    flight at a time (ordering: the follower must apply mutations in
    decree order for timetag floors to behave like the reference's
    single-channel shipping).
    """

    def __init__(self, stub, gpid: Tuple[int, int], dupid: int,
                 follower_meta: str, follower_app: str,
                 confirmed_decree: int = 0,
                 source_cluster_id: int = 1,
                 on_progress: Optional[Callable[[int, int], None]] = None,
                 fail_mode: str = "slow") -> None:
        self.stub = stub
        self.gpid = gpid
        self.dupid = dupid
        self.follower_meta = follower_meta
        self.follower_app = follower_app
        self.confirmed_decree = confirmed_decree
        self.source_cluster_id = source_cluster_id
        self.on_progress = on_progress
        # "slow": retry a rejected mutation forever (default, lossless);
        # "skip": after _FAIL_SKIP_RETRIES rejections of the SAME decree,
        # confirm past it (parity: duplication fail_mode FAIL_SKIP —
        # operator-sanctioned loss to un-wedge a stuck pipeline)
        self.fail_mode = fail_mode
        self._fail_decree: Optional[int] = None
        self._fail_count = 0
        self._fconfig: Optional[dict] = None  # follower app config
        # a FEW recent ask rids stay live: a re-ask must not discard a
        # SLOW (not lost) reply to an earlier ask — the same
        # retained-rid discipline the write path uses
        self._config_rids: "deque[int]" = deque(maxlen=4)
        self._config_ticks = 0  # ticks since the newest config ask
        # in-flight window: max decree + outstanding envelope rids. rid
        # → follower pidx, so a LATE ack from a superseded ship attempt
        # of the same window still completes that pidx (acks slower than
        # the re-drive cadence must not be discarded — that livelocks).
        self._inflight_decree: Optional[int] = None
        self._inflight_count = 0  # mutations in the in-flight window
        self._outstanding: Dict[int, int] = {}
        self._pending_pidx: set = set()
        self._redrive_decree: Optional[int] = None
        self._inflight_ticks = 0
        self._retry_limit = self.RETRY_TICKS
        # a REJECTED window retries on the next timer tick, never in
        # the same event cascade: the ack-triggered tick consumes this
        self._reject_cooldown = 0
        self._log_offset = 0
        self._log_generation: Optional[int] = None
        # per-envelope dup.ship spans (finish at ack), parented to the
        # source write's 2PC span ctx so `shell trace <id>` renders the
        # write crossing clusters as ONE stitched tree
        self._inflight_spans: Dict[int, object] = {}
        self.last_error: Optional[str] = None
        self._lag_ms = 0.0
        # per-dup observability on the "duplication" entity (reported up
        # config-sync so meta exposes cluster-wide dup health)
        ent = METRICS.entity(
            "duplication", f"{stub.name}.{gpid[0]}.{gpid[1]}.dup{dupid}",
            {"node": stub.name, "app_id": str(gpid[0]),
             "pidx": str(gpid[1]), "dupid": str(dupid)})
        self._g_lag_decrees = ent.gauge("dup_lag_decrees")
        self._g_lag_ms = ent.gauge("dup_lag_ms")
        self._c_shipped_bytes = ent.counter("dup_shipped_bytes")
        self._c_raw_bytes = ent.counter("dup_shipped_raw_bytes")
        self._c_confirmed = ent.counter("dup_confirmed_mutations")
        self._c_errors = ent.counter("dup_ship_error_count")
        self._c_rejects = ent.counter("dup_reject_count")
        self._c_skips = ent.counter("dup_skip_count")
        replica = stub.get_replica(gpid)
        if replica is not None:
            self._log_generation = replica.log.generation
            replica.duplicators.append(self)

    # ---- follower config -----------------------------------------------

    def _request_follower_config(self) -> None:
        rid = next(_RIDS)
        self._config_rids.append(rid)
        self.stub.net.send(self.stub.name, self.follower_meta,
                           "query_config",
                           {"app_name": self.follower_app, "rid": rid})

    def on_follower_config(self, payload: dict) -> bool:
        rid = payload.get("rid")
        if rid not in self._config_rids:
            return False
        if payload["err"] == 0:
            self._config_rids.clear()
            self._fconfig = {
                "app_id": payload["app_id"],
                "partition_count": payload["partition_count"],
                "configs": payload["configs"],
            }
        else:
            # an error reply settles only ITS ask: a newer in-flight
            # ask's (possibly successful) reply must stay acceptable
            self._config_rids.remove(rid)
        return True

    # ---- shipping ------------------------------------------------------

    RETRY_TICKS = 3  # in-flight ship attempts re-drive after this many

    def tick(self) -> None:
        """Load → ship the next window of committed mutations."""
        from pegasus_tpu.replica.replica import PartitionStatus

        replica = self.stub.get_replica(self.gpid)
        if replica is None or replica.status != PartitionStatus.PRIMARY:
            return  # dup runs on the primary only (meta re-homes us)
        last_committed = replica.last_committed_decree
        self._g_lag_decrees.set(
            max(0, last_committed - self.confirmed_decree))
        if self._reject_cooldown:
            # a rejection retries on the NEXT timer tick, not inside
            # the same delivery cascade — an unhealthy follower (lease-
            # lapsed, mid-failover) would otherwise feed a tight
            # ship→reject→re-resolve→re-ship storm that starves the
            # very timer rounds (beacons, cures) that heal it
            self._reject_cooldown -= 1
            return
        if self._inflight_decree is not None:
            # waiting on follower acks — but a LOST shipped envelope (or
            # a lost ack) must not wedge the pipeline forever: after a
            # few ticks, re-resolve and re-ship the same window.
            # Re-shipping is safe — dup ops are idempotent on the
            # follower (timetag conflict resolution discards the stale
            # double-apply). The old rids stay registered (see
            # _ship_window) and the re-drive interval backs off
            # exponentially, so a follower whose RTT exceeds the base
            # cadence converges instead of livelocking.
            self._inflight_ticks += 1
            if self._inflight_ticks < self._retry_limit:
                return
            # modest backoff cap: retained rids (below) already let a
            # slow follower converge via LATE acks, so the backoff only
            # reduces re-ship traffic — a large cap would instead gut
            # convergence under LINK LOSS, where re-drives are the only
            # recovery (seed-sweep regression on case-608)
            self._retry_limit = min(self._retry_limit * 2, 12)
            self._fconfig = None
            self._redrive_decree = self._inflight_decree
            self._inflight_decree = None
            self._inflight_ticks = 0
        if self._fconfig is None:
            # the config ask (or its reply) can be LOST: re-issue with a
            # fresh rid after a few ticks, or a single dropped message
            # wedges the whole pipeline forever (seed-sweep finding —
            # the canonical schedule never dropped this message)
            if not self._config_rids:
                self._request_follower_config()
                self._config_ticks = 0
            else:
                self._config_ticks += 1
                if self._config_ticks >= self.RETRY_TICKS:
                    self._request_follower_config()
                    self._config_ticks = 0
            return
        log = replica.log
        if log.generation != self._log_generation:
            self._log_offset = 0
            self._log_generation = log.generation
        cap_n = int(FLAGS.get("pegasus.dup", "ship_batch_mutations"))
        solo_wire = cap_n <= 1
        cap_n = max(1, cap_n)
        if self._fail_count:
            # fail_mode=skip is counting rejections: shrink to solo
            # windows so retries (and an eventual abandon) isolate the
            # poison DECREE instead of skipping a whole window
            cap_n = 1
        cap_b = int(FLAGS.get("pegasus.dup", "ship_batch_bytes"))
        governor = getattr(self.stub, "dup_governor", None)
        if governor is not None:
            budget = governor.window_budget()
            if budget is not None:
                cap_b = min(cap_b, budget)
        window: List[Tuple[Mutation, int]] = []
        est = 0
        prev_end = self._log_offset
        for mu, frame_end in log.read_tail(self._log_offset):
            if mu.decree > last_committed:
                break
            if mu.decree <= self.confirmed_decree:
                self._log_offset = frame_end
                prev_end = frame_end
                continue
            if (self._redrive_decree is not None
                    and mu.decree > self._redrive_decree):
                # a re-drive re-ships EXACTLY the superseded window (not
                # a freshly-grown one), so the retained rids' late acks
                # still match what is in flight
                break
            window.append((mu, frame_end))
            est += frame_end - prev_end
            prev_end = frame_end
            if len(window) >= cap_n or est >= cap_b:
                break  # floor: the first mutation always gets in
        if not window:
            # nothing below the (possibly stale) re-drive cap: drop it
            # so the next tick can load fresh decrees — a retained cap
            # above `confirmed` would otherwise wedge loading forever
            self._redrive_decree = None
            self._lag_ms = 0.0
            self._g_lag_ms.set(0.0)
            return
        clock = self.stub.clock
        now_ms = (clock() if clock is not None else 0.0) * 1000.0
        self._lag_ms = max(0.0, now_ms - window[0][0].timestamp_us / 1e3) \
            if now_ms else 0.0
        self._g_lag_ms.set(round(self._lag_ms, 1))
        self._ship_window(window, solo_wire)

    def _finish_spans(self) -> None:
        for span in self._inflight_spans.values():
            span.finish()
        self._inflight_spans.clear()

    def _abort_ship(self, pidx: int) -> None:
        """Mid-loop abort (follower partition unowned): drop the config
        and retry later. The rids/pidxs staged by THIS aborted attempt
        are cleared — a late ack for one of them must not reset
        `_retry_limit`/`_inflight_ticks` for a window that is no longer
        in flight (regression: tests/test_cross_cluster_dup.py)."""
        self._fconfig = None
        self._inflight_decree = None
        self._inflight_count = 0
        self._outstanding = {}
        self._pending_pidx = set()
        self._finish_spans()
        self._c_errors.increment()
        self.last_error = f"follower partition {pidx} unowned"

    def _ship_window(self, window: List[Tuple[Mutation, int]],
                     solo_wire: bool) -> None:
        from pegasus_tpu.storage.block_codec import deflate_payload
        from pegasus_tpu.utils import tracing

        count = self._fconfig["partition_count"]
        by_pidx: Dict[int, List[tuple]] = {}
        replica = self.stub.get_replica(self.gpid)
        dup_ctxs = getattr(replica, "dup_trace_ctxs", None) \
            if replica is not None else None
        ctx0 = None
        for mu, _fe in window:
            mu_now = max(0, mu.timestamp_us // 1_000_000
                         - PEGASUS_EPOCH_BEGIN)
            for i, wo in enumerate(mu.ops):
                timetag = generate_timetag(mu.timestamp_us + i,
                                           self.source_cluster_id, False)
                for key, dup_op, req in self._dup_ops(wo, timetag,
                                                      mu_now):
                    by_pidx.setdefault(key_hash(key) % count, []).append(
                        (dup_op, req))
            if ctx0 is None and dup_ctxs:
                # the first traced mutation's 2PC ctx parents the ship
                # spans: one stitched tree across clusters
                ctx0 = dup_ctxs.get(mu.decree)
        max_decree = window[-1][0].decree
        frame_end = window[-1][1]
        if not by_pidx:
            # nothing shippable (e.g. empty mutations): confirm, move on
            self._redrive_decree = None
            self._advance(max_decree, frame_end)
            return
        self._inflight_decree = max_decree
        self._inflight_frame_end = frame_end
        self._inflight_count = len(window)
        if max_decree != self._redrive_decree:
            self._finish_spans()
            self._outstanding = {}  # new window: prior rids are dead
        self._redrive_decree = None
        self._pending_pidx = set(by_pidx)
        self._inflight_ticks = 0
        auth = None
        if getattr(self.stub, "auth_secret", None):
            from pegasus_tpu.security.auth import (
                NODE_USER,
                make_credentials,
            )

            auth = make_credentials(NODE_USER, self.stub.auth_secret)
        governor = getattr(self.stub, "dup_governor", None)
        app_id = self._fconfig["app_id"]
        for pidx, ops in by_pidx.items():
            primary = self._fconfig["configs"][pidx]["primary"]
            if not primary:
                self._abort_ship(pidx)
                return
            rid = next(_RIDS)
            self._outstanding[rid] = pidx
            span = None
            if ctx0 is not None:
                span = tracing.ring_for(self.stub.name).start(
                    f"dup.ship.{app_id}.{pidx}", parent_ctx=ctx0)
                self._inflight_spans[rid] = span
            # deliberately NO deadline on duplication-shipped writes:
            # this is replication-class traffic (the log-GC floor waits
            # on it), so it must never be fast-failed as abandoned —
            # same exemption the dispatcher's overload shedding applies
            if solo_wire:
                payload = {"gpid": (app_id, pidx), "rid": rid,
                           "ops": ops, "auth": auth}
                if span is not None:
                    payload["trace"] = span.ctx()
                wire = sum(len(encode_write(o, r)) for o, r in ops)
                self._c_shipped_bytes.increment(wire)
                self._c_raw_bytes.increment(wire)
                if governor is not None:
                    governor.note_shipped(wire)
                self.stub.net.send(self.stub.name, primary,
                                   "client_write", payload)
                continue
            parts = []
            for dup_op, req in ops:
                eb = encode_write(dup_op, req)
                parts.append(_LEN.pack(len(eb)))
                parts.append(eb)
            blob = b"".join(parts)
            mode, stored = deflate_payload(blob)
            self._c_shipped_bytes.increment(len(stored))
            self._c_raw_bytes.increment(len(blob))
            if governor is not None:
                governor.note_shipped(len(stored))
            self.stub.net.send(self.stub.name, primary,
                               "dup_apply_batch", {
                                   "gpid": (app_id, pidx), "rid": rid,
                                   "dupid": self.dupid,
                                   "ops_blob": stored,
                                   "blob_mode": mode,
                                   "raw_len": len(blob),
                                   "n_ops": len(ops),
                                   "max_decree": max_decree,
                                   "auth": auth,
                                   # explicit ctx (or None — never let
                                   # ambient stamping mis-tag a batch)
                                   "trace": (span.ctx()
                                             if span is not None
                                             else None)})

    @staticmethod
    def _timetag_cluster(timetag: int) -> int:
        return (timetag >> 1) & 0x7F

    def _dup_ops(self, wo, timetag: int, mu_now: int):
        """Translate one logged write op into (key, dup_op, request)s."""
        if wo.op in (OP_DUP_PUT, OP_DUP_REMOVE):
            # a dup-tagged op is either (a) an idempotent-translated
            # LOCAL atomic (timetag minted with OUR cluster id) — ship
            # verbatim — or (b) a write RECEIVED from another cluster's
            # duplication: re-shipping those would echo master-master
            # writes back and forth forever (the reference's
            # origin-cluster filter)
            if (self._timetag_cluster(wo.request[-1])
                    == self.source_cluster_id):
                yield wo.request[0], wo.op, wo.request
            return
        if wo.op in ATOMIC_OPS:
            # unreachable on tables that enabled duplication BEFORE the
            # write (client_write idempotent-translates); mutations
            # logged before dup-add may still carry raw atomic ops —
            # those cannot ship safely (re-execution) and are skipped,
            # matching the reference's requirement that idempotence be
            # enabled before adding a duplication
            return
        if wo.op == OP_PUT:
            key, user_data, expire_ts = wo.request
            yield key, OP_DUP_PUT, (key, user_data, expire_ts, timetag)
        elif wo.op == OP_REMOVE:
            (key,) = wo.request
            yield key, OP_DUP_REMOVE, (key, timetag)
        elif wo.op == OP_MULTI_PUT:
            expire_ts = expire_ts_from_ttl(wo.request.expire_ts_seconds,
                                           now=mu_now)
            for kv in wo.request.kvs:
                key = generate_key(wo.request.hash_key, kv.key)
                yield key, OP_DUP_PUT, (key, kv.value, expire_ts, timetag)
        elif wo.op == OP_MULTI_REMOVE:
            for sk in wo.request.sort_keys:
                key = generate_key(wo.request.hash_key, sk)
                yield key, OP_DUP_REMOVE, (key, timetag)

    def on_write_reply(self, payload: dict) -> bool:
        rid = payload.get("rid")
        if rid not in self._outstanding:
            return False
        span = self._inflight_spans.pop(rid, None)
        if span is not None:
            span.finish()
        governor = getattr(self.stub, "dup_governor", None)
        if governor is not None:
            # follower foreground pressure rides the batch ack: the
            # governor backs catch-up off before the follower sheds
            governor.on_follower_pressure(payload.get("node", "?"),
                                          payload.get("pressure"))
        if payload["err"] != 0:
            decree = self._inflight_decree
            self._c_rejects.increment()
            self._c_errors.increment()
            self.last_error = (f"follower rejected err={payload['err']} "
                               f"decree={decree}")
            _DUP_LOG.log(f"dup.reject.{self.gpid[0]}.{self.gpid[1]}",
                         _DupError(self.last_error))
            if self.fail_mode == "skip" and decree is not None:
                if self._fail_decree == decree:
                    self._fail_count += 1
                else:
                    self._fail_decree, self._fail_count = decree, 1
                if (self._fail_count >= _FAIL_SKIP_RETRIES
                        and self._inflight_count <= 1):
                    # operator chose loss over a wedged pipeline:
                    # confirm past the poison mutation and move on —
                    # LOUDLY (sanctioned loss must still be visible)
                    self._c_skips.increment()
                    _DUP_LOG.log(
                        f"dup.skip.{self.gpid[0]}.{self.gpid[1]}",
                        _DupError(f"fail_mode=skip abandoned decree "
                                  f"{decree} after {self._fail_count} "
                                  f"rejections (dupid {self.dupid})"))
                    self._advance(decree, self._inflight_frame_end)
                    self._fail_decree, self._fail_count = None, 0
                    self._inflight_decree = None
                    self._inflight_count = 0
                    self._outstanding = {}
                    self._pending_pidx = set()
                    self._finish_spans()
                    return True
            # follower rejected (failover/stale config): re-resolve and
            # re-ship the whole window — idempotent on the follower —
            # from the next TIMER tick (paced, see _reject_cooldown)
            self._fconfig = None
            self._inflight_decree = None
            self._outstanding = {}
            self._pending_pidx = set()
            self._finish_spans()
            self._reject_cooldown = 1
            return True
        pidx = self._outstanding.pop(rid)
        self._pending_pidx.discard(pidx)
        # an ack is PROGRESS: the link works — stop backing off AND
        # restart the re-drive clock (without resetting the tick count a
        # shrunken limit would fire a spurious re-drive next tick)
        self._retry_limit = self.RETRY_TICKS
        self._inflight_ticks = 0
        if not self._pending_pidx and self._inflight_decree is not None:
            self._advance(self._inflight_decree, self._inflight_frame_end)
            self._inflight_decree = None
            self._inflight_count = 0
            self._outstanding = {}
            # the rejected decree shipped after all: clear the skip
            # bookkeeping, or one TRANSIENT rejection would pin the
            # window to solo (cap_n=1) for the session's whole lifetime
            self._fail_decree, self._fail_count = None, 0
        return True

    def _advance(self, decree: int, frame_end: int) -> None:
        self._c_confirmed.increment(max(0, decree - self.confirmed_decree))
        self.confirmed_decree = decree
        self._log_offset = frame_end
        if self.on_progress is not None:
            self.on_progress(self.dupid, decree)

    # ---- observability (config-sync report / dup.stats verb) -----------

    def stats(self) -> dict:
        replica = self.stub.get_replica(self.gpid)
        last_committed = (replica.last_committed_decree
                          if replica is not None else 0)
        return {
            "gpid": list(self.gpid),
            "dupid": self.dupid,
            # whether THIS replica has the drill fence applied when the
            # report was built: the drain check needs positive evidence
            # the fence reached the replica — a report merely ARRIVING
            # after the fence decision could have been built before the
            # env landed, while a not-yet-fenced replica kept acking
            "fenced": bool(replica is not None
                           and replica.server.app_envs.get("dup.fence")),
            "follower_meta": self.follower_meta,
            "follower_app": self.follower_app,
            "fail_mode": self.fail_mode,
            "confirmed": self.confirmed_decree,
            "last_committed": last_committed,
            "lag_decrees": max(0, last_committed - self.confirmed_decree),
            "lag_ms": round(self._lag_ms, 1),
            "inflight_decree": self._inflight_decree,
            "shipped_bytes": self._c_shipped_bytes.value(),
            "shipped_raw_bytes": self._c_raw_bytes.value(),
            "confirmed_mutations": self._c_confirmed.value(),
            "error_count": self._c_errors.value(),
            "reject_count": self._c_rejects.value(),
            "skip_count": self._c_skips.value(),
            "last_error": self.last_error,
        }
