"""FsManager: multi-data-dir layout, capacity tracking, trash cleanup.

Parity: src/common/fs_manager.h:115 (dir_node capacity tracking +
per-disk replica placement), src/replica/disk_cleaner.* (removed
replicas rename to trash and age out instead of vanishing instantly),
and src/replica/replica_disk_migrator.h (move a replica between disks).
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

Gpid = Tuple[int, int]

TRASH_SUFFIX = ".gar"


class FsManager:
    def __init__(self, data_dirs: List[str]) -> None:
        if not data_dirs:
            raise ValueError("need at least one data dir")
        self.data_dirs = [os.path.abspath(d) for d in data_dirs]
        for d in self.data_dirs:
            os.makedirs(d, exist_ok=True)

    # ---- layout --------------------------------------------------------

    @staticmethod
    def _entry_name(gpid: Gpid) -> str:
        return f"{gpid[0]}.{gpid[1]}"

    def scan_replicas(self) -> Dict[Gpid, str]:
        """gpid -> replica dir, across every data dir (parity: the boot
        scan, replica_stub.cpp:594 load_replicas per disk)."""
        out: Dict[Gpid, str] = {}
        for d in self.data_dirs:
            for entry in sorted(os.listdir(d)):
                if entry.endswith(".migrating"):
                    # crashed mid-migration copy: the source is intact
                    shutil.rmtree(os.path.join(d, entry),
                                  ignore_errors=True)
                    continue
                parts = entry.split(".")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    out[(int(parts[0]), int(parts[1]))] = os.path.join(
                        d, entry)
        return out

    def dir_of(self, gpid: Gpid) -> Optional[str]:
        for d in self.data_dirs:
            path = os.path.join(d, self._entry_name(gpid))
            if os.path.isdir(path):
                return path
        return None

    def replica_dir(self, gpid: Gpid) -> str:
        """Existing home, or a placement on the least-loaded disk
        (parity: fs_manager picks the dir with most headroom; replica
        COUNT is the capacity proxy here — byte usage shifts with
        compaction and would make placement flappy)."""
        existing = self.dir_of(gpid)
        if existing is not None:
            return existing
        counts = {d: 0 for d in self.data_dirs}
        for _g, path in self.scan_replicas().items():
            counts[os.path.dirname(path)] += 1
        best = min(self.data_dirs, key=lambda d: (counts[d], d))
        return os.path.join(best, self._entry_name(gpid))

    # ---- capacity ------------------------------------------------------

    def stats(self) -> List[dict]:
        out = []
        for d in self.data_dirs:
            replicas = []
            used = 0
            for entry in sorted(os.listdir(d)):
                path = os.path.join(d, entry)
                if not os.path.isdir(path) or entry.endswith(TRASH_SUFFIX):
                    continue
                parts = entry.split(".")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    replicas.append(entry)
                    used += _dir_bytes(path)
            disk = shutil.disk_usage(d)
            out.append({"dir": d, "replicas": replicas,
                        "used_bytes": used,
                        "disk_total": disk.total,
                        "disk_available": disk.free})
        return out

    # ---- trash (parity: disk_cleaner — .gar aging) ---------------------

    def trash_replica(self, gpid: Gpid) -> Optional[str]:
        """Removed replicas move to trash (name.<ts>.gar) instead of
        instant deletion — an operator can still recover from a wrong
        GC decision until the cleaner ages it out."""
        path = self.dir_of(gpid)
        if path is None:
            return None
        dest = f"{path}.{int(time.time())}{TRASH_SUFFIX}"
        os.rename(path, dest)
        return dest

    def clean_trash(self, max_age_seconds: float = 86400.0) -> List[str]:
        removed = []
        now = time.time()
        for d in self.data_dirs:
            for entry in os.listdir(d):
                if not entry.endswith(TRASH_SUFFIX):
                    continue
                try:
                    ts = int(entry[:-len(TRASH_SUFFIX)].rsplit(".", 1)[1])
                except (IndexError, ValueError):
                    ts = 0
                if now - ts >= max_age_seconds:
                    shutil.rmtree(os.path.join(d, entry),
                                  ignore_errors=True)
                    removed.append(entry)
        return removed

    # ---- migration (parity: replica_disk_migrator.h) -------------------

    def migrate(self, gpid: Gpid, dest_data_dir: str) -> str:
        """Copy a (closed) replica dir to another disk and retire the
        old copy to trash; caller must have closed the replica first and
        reopens it from the returned path."""
        dest_data_dir = os.path.abspath(dest_data_dir)
        if dest_data_dir not in self.data_dirs:
            raise ValueError(f"{dest_data_dir} is not a managed data dir")
        src = self.dir_of(gpid)
        if src is None:
            raise ValueError(f"replica {gpid} not found")
        if os.path.dirname(src) == dest_data_dir:
            return src
        dest = os.path.join(dest_data_dir, self._entry_name(gpid))
        # copy under a temp name, then rename: a crash mid-copy must not
        # leave a truncated dir with the REPLICA'S name that could shadow
        # the intact source at the next boot scan
        tmp = dest + ".migrating"
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(dest, ignore_errors=True)
        shutil.copytree(src, tmp)
        os.rename(src, f"{src}.{int(time.time())}{TRASH_SUFFIX}")
        os.rename(tmp, dest)
        return dest


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total
