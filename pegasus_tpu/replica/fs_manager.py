"""FsManager: multi-data-dir layout, capacity tracking, trash cleanup,
and per-dir health.

Parity: src/common/fs_manager.h:115 (dir_node capacity tracking +
per-disk replica placement + disk_status NORMAL/SPACE_INSUFFICIENT/
IO_ERROR — fs_manager.h:52), src/replica/disk_cleaner.* (removed
replicas rename to trash and age out instead of vanishing instantly),
and src/replica/replica_disk_migrator.h (move a replica between disks).

Health: the stub reports storage OSErrors here (`note_io_error`); a dir
that produced EIO-class failures goes IO_ERROR, ENOSPC goes
SPACE_INSUFFICIENT, and `replica_dir` stops placing NEW replicas on
sick dirs (existing replicas stay until the quarantine/cure machinery
moves them — the reference likewise only excludes sick dir_nodes from
placement, fs_manager.cpp:select_target_dir_node).
"""

from __future__ import annotations

import errno as _errno
import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

Gpid = Tuple[int, int]

TRASH_SUFFIX = ".gar"

# per-dir health states (parity: disk_status::type, fs_manager.h:52)
DIR_NORMAL = "NORMAL"
DIR_SPACE_INSUFFICIENT = "SPACE_INSUFFICIENT"
DIR_IO_ERROR = "IO_ERROR"


class FsManager:
    def __init__(self, data_dirs: List[str]) -> None:
        if not data_dirs:
            raise ValueError("need at least one data dir")
        self.data_dirs = [os.path.abspath(d) for d in data_dirs]
        for d in self.data_dirs:
            os.makedirs(d, exist_ok=True)
        self._dir_status: Dict[str, str] = {
            d: DIR_NORMAL for d in self.data_dirs}
        self._dir_errors: Dict[str, int] = {d: 0 for d in self.data_dirs}

    # ---- layout --------------------------------------------------------

    @staticmethod
    def _entry_name(gpid: Gpid) -> str:
        return f"{gpid[0]}.{gpid[1]}"

    def scan_replicas(self) -> Dict[Gpid, str]:
        """gpid -> replica dir, across every data dir (parity: the boot
        scan, replica_stub.cpp:594 load_replicas per disk)."""
        out: Dict[Gpid, str] = {}
        for d in self.data_dirs:
            for entry in sorted(os.listdir(d)):
                if entry.endswith(".migrating"):
                    # crashed mid-migration copy: the source is intact
                    shutil.rmtree(os.path.join(d, entry),
                                  ignore_errors=True)
                    continue
                parts = entry.split(".")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    out[(int(parts[0]), int(parts[1]))] = os.path.join(
                        d, entry)
        return out

    def dir_of(self, gpid: Gpid) -> Optional[str]:
        for d in self.data_dirs:
            path = os.path.join(d, self._entry_name(gpid))
            if os.path.isdir(path):
                return path
        return None

    def replica_dir(self, gpid: Gpid) -> str:
        """Existing home, or a placement on the least-loaded HEALTHY
        disk (parity: fs_manager picks the dir with most headroom and
        skips non-NORMAL dir_nodes; replica COUNT is the capacity proxy
        here — byte usage shifts with compaction and would make
        placement flappy). When every dir is sick the least-loaded one
        is still returned — refusing placement entirely would wedge
        cures, and the reference degrades the same way."""
        existing = self.dir_of(gpid)
        if existing is not None:
            return existing
        candidates = self.healthy_dirs() or self.data_dirs
        counts = {d: 0 for d in self.data_dirs}
        for _g, path in self.scan_replicas().items():
            counts[os.path.dirname(path)] += 1
        best = min(candidates, key=lambda d: (counts[d], d))
        return os.path.join(best, self._entry_name(gpid))

    # ---- health (parity: fs_manager dir_node status) -------------------

    def healthy_dirs(self) -> List[str]:
        return [d for d in self.data_dirs
                if self._dir_status[d] == DIR_NORMAL]

    def dir_status(self, data_dir: str) -> str:
        return self._dir_status[os.path.abspath(data_dir)]

    def dir_of_path(self, path: str) -> Optional[str]:
        """The managed data dir containing `path` (any depth), or None."""
        p = os.path.abspath(path)
        for d in self.data_dirs:
            if p == d or p.startswith(d + os.sep):
                return d
        return None

    def note_io_error(self, path: str, exc: OSError) -> Optional[str]:
        """Record a storage OSError against the owning dir: ENOSPC
        marks SPACE_INSUFFICIENT, everything else IO_ERROR. Returns the
        dir marked (None when the path is outside every managed dir).
        An IO_ERROR verdict is sticky over SPACE_INSUFFICIENT — a disk
        that both filled and errored is treated as broken."""
        d = self.dir_of_path(path)
        if d is None:
            return None
        self._dir_errors[d] += 1
        status = (DIR_SPACE_INSUFFICIENT
                  if getattr(exc, "errno", None) == _errno.ENOSPC
                  else DIR_IO_ERROR)
        if not (self._dir_status[d] == DIR_IO_ERROR
                and status == DIR_SPACE_INSUFFICIENT):
            self._dir_status[d] = status
        return d

    def mark_dir_normal(self, data_dir: str) -> None:
        """Operator reset (disk replaced / space freed)."""
        self._dir_status[os.path.abspath(data_dir)] = DIR_NORMAL

    def health(self) -> List[dict]:
        """Per-dir state + error counts (shell `disk_health`)."""
        out = []
        for d in self.data_dirs:
            try:
                disk = shutil.disk_usage(d)
                avail = disk.free
            except OSError:
                avail = -1
            out.append({"dir": d, "status": self._dir_status[d],
                        "io_errors": self._dir_errors[d],
                        "disk_available": avail})
        return out

    # ---- capacity ------------------------------------------------------

    def stats(self) -> List[dict]:
        out = []
        for d in self.data_dirs:
            replicas = []
            used = 0
            for entry in sorted(os.listdir(d)):
                path = os.path.join(d, entry)
                if not os.path.isdir(path) or entry.endswith(TRASH_SUFFIX):
                    continue
                parts = entry.split(".")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    replicas.append(entry)
                    used += _dir_bytes(path)
            disk = shutil.disk_usage(d)
            out.append({"dir": d, "replicas": replicas,
                        "used_bytes": used,
                        "disk_total": disk.total,
                        "disk_available": disk.free})
        return out

    # ---- trash (parity: disk_cleaner — .gar aging) ---------------------

    def trash_replica(self, gpid: Gpid) -> Optional[str]:
        """Removed replicas move to trash (name.<ts>.gar) instead of
        instant deletion — an operator can still recover from a wrong
        GC decision until the cleaner ages it out."""
        path = self.dir_of(gpid)
        if path is None:
            return None
        dest = f"{path}.{int(time.time())}{TRASH_SUFFIX}"
        os.rename(path, dest)
        return dest

    def clean_trash(self, max_age_seconds: float = 86400.0) -> List[str]:
        removed = []
        now = time.time()
        for d in self.data_dirs:
            for entry in os.listdir(d):
                if not entry.endswith(TRASH_SUFFIX):
                    continue
                try:
                    ts = int(entry[:-len(TRASH_SUFFIX)].rsplit(".", 1)[1])
                except (IndexError, ValueError):
                    ts = 0
                if now - ts >= max_age_seconds:
                    shutil.rmtree(os.path.join(d, entry),
                                  ignore_errors=True)
                    removed.append(entry)
        return removed

    # ---- migration (parity: replica_disk_migrator.h) -------------------

    def migrate(self, gpid: Gpid, dest_data_dir: str) -> str:
        """Copy a (closed) replica dir to another disk and retire the
        old copy to trash; caller must have closed the replica first and
        reopens it from the returned path."""
        dest_data_dir = os.path.abspath(dest_data_dir)
        if dest_data_dir not in self.data_dirs:
            raise ValueError(f"{dest_data_dir} is not a managed data dir")
        src = self.dir_of(gpid)
        if src is None:
            raise ValueError(f"replica {gpid} not found")
        if os.path.dirname(src) == dest_data_dir:
            return src
        dest = os.path.join(dest_data_dir, self._entry_name(gpid))
        # copy under a temp name, then rename: a crash mid-copy must not
        # leave a truncated dir with the REPLICA'S name that could shadow
        # the intact source at the next boot scan
        tmp = dest + ".migrating"
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(dest, ignore_errors=True)
        shutil.copytree(src, tmp)
        os.rename(src, f"{src}.{int(time.time())}{TRASH_SUFFIX}")
        os.rename(tmp, dest)
        return dest


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total
