"""Mutation: the unit of replication.

Parity: src/replica/mutation.h:79 — a mutation carries a ballot, a decree,
the primary's last_committed_decree (piggy-backed so secondaries advance
their commit point, replica_2pc.cpp:344,709), a primary-assigned
timestamp (determinism of value timetags across replicas), and one or
more client write requests. Batching rule (mutation.cpp:390,553): multiple
batchable writes (put/remove/multi_*) share a mutation; atomic ops
(incr/cas/cam) ride alone.

Wire/log format:
    [u64 ballot][u64 decree][u64 last_committed][u64 timestamp_us]
    [u32 n_ops] { [u32 len][encoded write] }*
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Tuple

from pegasus_tpu.rpc.codec import decode_write, encode_write

_HDR = struct.Struct("<QQQQI")

# ops that may share a mutation (parity: rpc_request_is_write_allow_batch)
from pegasus_tpu.rpc.codec import (  # noqa: E402
    OP_CAM,
    OP_CAS,
    OP_DUP_PUT,
    OP_DUP_REMOVE,
    OP_INCR,
    OP_INGEST,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)

BATCHABLE_OPS = {OP_PUT, OP_REMOVE, OP_MULTI_PUT, OP_MULTI_REMOVE,
                 OP_DUP_PUT, OP_DUP_REMOVE}
# ingestion rides alone like atomic ops (a whole-SST apply must own its
# decree; parity: bulk-load mutations never batch)
ATOMIC_OPS = {OP_INCR, OP_CAS, OP_CAM, OP_INGEST}


@dataclass
class WriteOp:
    op: int
    request: Any


@dataclass
class Mutation:
    ballot: int
    decree: int
    last_committed: int
    timestamp_us: int
    ops: List[WriteOp] = field(default_factory=list)

    def encode(self) -> bytes:
        parts = [_HDR.pack(self.ballot, self.decree, self.last_committed,
                           self.timestamp_us, len(self.ops))]
        for wo in self.ops:
            blob = encode_write(wo.op, wo.request)
            parts.append(struct.pack("<I", len(blob)))
            parts.append(blob)
        return b"".join(parts)

    @staticmethod
    def decode(data: bytes) -> "Mutation":
        ballot, decree, last_committed, ts, n = _HDR.unpack_from(data, 0)
        pos = _HDR.size
        ops: List[WriteOp] = []
        for _ in range(n):
            (length,) = struct.unpack_from("<I", data, pos)
            pos += 4
            op, req, end = decode_write(data, pos)
            if end != pos + length:
                raise ValueError("mutation op length mismatch")
            ops.append(WriteOp(op, req))
            pos = end
        return Mutation(ballot, decree, last_committed, ts, ops)
