"""Replica: one PacificA participant for one partition.

Parity: src/replica/replica.h + replica_2pc.cpp + replica_config.cpp +
replica_learn.cpp. Core invariants mirrored:

- Roles PS_PRIMARY / PS_SECONDARY / PS_POTENTIAL_SECONDARY / PS_INACTIVE /
  PS_ERROR, changed only by ballot-bumping config assignments from meta
  (here: `assign_config`).
- Write path (replica_2pc.cpp:113,328): primary assigns decree =
  max_prepared + 1, prepares locally (prepare list + private log), sends
  PREPARE to every secondary AND every potential secondary whose learn
  has reached the prepare-start point; commits when ALL of them ack
  (PacificA: unanimous ack of the configuration, not majority —
  `ack_prepare_message` waits for every member; a dead member is removed
  by reconfiguration, not voted around).
- Secondaries advance their commit point from the piggy-backed
  last_committed in each prepare (COMMIT_TO_DECREE_HARD,
  replica_2pc.cpp:709) and from group checks (replica_check.cpp:212).
- Reads served by the primary only, gated on a caught-up commit point
  (replica.cpp:407-426).
- Learning (replica_learn.cpp:88,361): a potential secondary catches up
  via LT_LOG (mutations read back from the primary's private log) or
  LT_APP (checkpoint copy + log tail), then notifies completion and is
  upgraded by a config change.

Determinism: translate-at-apply for atomic ops is deterministic across
replicas because the decree order, the mutation's primary-assigned
timestamp, and the derived `now` are identical everywhere.
"""

from __future__ import annotations

import enum
import os
import shutil
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from pegasus_tpu.base.value_schema import PEGASUS_EPOCH_BEGIN
from pegasus_tpu.replica.mutation import (
    ATOMIC_OPS,
    BATCHABLE_OPS,
    Mutation,
    WriteOp,
)
from pegasus_tpu.replica.mutation_log import MutationLog
from pegasus_tpu.replica.prepare_list import (
    COMMIT_ALL_READY,
    COMMIT_TO_DECREE_HARD,
    COMMIT_TO_DECREE_SOFT,
    PrepareList,
)
from pegasus_tpu.rpc.codec import (
    OP_CAM,
    OP_CAS,
    OP_DUP_PUT,
    OP_DUP_REMOVE,
    OP_INCR,
    OP_INGEST,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)
from pegasus_tpu.server.partition_server import PartitionServer
from pegasus_tpu.utils.errors import ErrorCode
from pegasus_tpu.utils.thread_check import SerialAccessChecker


def _serial(fn):
    """Guard a replica entry point with the single-writer checker
    (parity: _checker.only_one_thread_access(), replica_2pc.cpp:115):
    concurrent entry from a second thread = a missing node lock, raised
    loudly at the site instead of corrupting replication state."""
    def wrapped(self, *args, **kwargs):
        with self._access:
            return fn(self, *args, **kwargs)
    wrapped.__name__ = fn.__name__
    wrapped.__doc__ = fn.__doc__
    return wrapped

PREPARE_LIST_CAPACITY = 1024


class ReplicaBusyError(RuntimeError):
    """Write-queue overload: the mutation queue is full, or a
    non-batchable op is stuck behind an in-flight round. RETRYABLE —
    the stub maps it to ERR_BUSY so the client's backoff machinery
    handles write overload exactly like read shedding (never
    ERR_INVALID_STATE, which would burn a config refresh per retry)."""


class PartitionStatus(enum.IntEnum):
    INACTIVE = 0
    ERROR = 1
    PRIMARY = 2
    SECONDARY = 3
    POTENTIAL_SECONDARY = 4


@dataclass
class ReplicaConfig:
    """Parity: partition_configuration (idl/dsn.layer2.thrift:34-46)."""

    ballot: int
    primary: str
    secondaries: List[str] = field(default_factory=list)


# learn types (parity: replica_learn.cpp LT_CACHE/LT_LOG/LT_APP)
LT_LOG = "log"
LT_APP = "app"


class Replica:
    """One partition's consensus participant. Messages travel through a
    transport with `send(src, dst, msg_type, payload)`; the owner
    registers `on_message` as the receive handler."""

    def __init__(self, name: str, data_dir: str, transport,
                 app_id: int = 1, pidx: int = 0, partition_count: int = 1,
                 clock: Optional[Callable[[], float]] = None,
                 cluster_id: int = 1) -> None:
        self.name = name
        self.data_dir = data_dir
        self.transport = transport
        self.clock = clock or time.time
        self.server = PartitionServer(
            os.path.join(data_dir, "app"), app_id=app_id, pidx=pidx,
            partition_count=partition_count, cluster_id=cluster_id)
        self.log = MutationLog(os.path.join(data_dir, "plog", "mlog.bin"))

        self.status = PartitionStatus.INACTIVE
        self.config = ReplicaConfig(ballot=0, primary="", secondaries=[])
        self._access = SerialAccessChecker(
            f"replica {app_id}.{pidx}@{name}")
        # fail-point site names are hot-path lookups: built once
        self._fp_primary_plog = f"{name}::primary_plog_append"
        self.prepare_list = PrepareList(
            self.server.engine.last_committed_decree, PREPARE_LIST_CAPACITY,
            self._apply_mutation)
        # boot: re-prepare logged mutations beyond the applied decree, and
        # seed the monotonic-timestamp floor from replayed mutations (a
        # restarted primary must not mint timestamps at or below ones it
        # already shipped to duplication followers)
        for mu in self.log.replay(self.log.path):
            if mu.decree > self.prepare_list.last_committed_decree:
                self.prepare_list.prepare(mu)
            self._boot_timestamp_floor = max(
                getattr(self, "_boot_timestamp_floor", 0),
                mu.timestamp_us + max(len(mu.ops), 1) - 1)

        # primary-assigned mutation timestamps must be strictly monotonic
        # (duplication conflict resolution and timetag uniqueness depend on
        # it; the reference guarantees this per-primary) — seeded from the
        # log replay above so restarts don't regress the floor
        self._last_timestamp_us = getattr(self, "_boot_timestamp_floor", 0)
        # duplicators attach here; log GC must not outrun their progress
        self.duplicators: List = []
        # decree -> the write's 2PC span ctx (sampled writes only):
        # duplication parents its dup.ship spans here so a traced write
        # renders as ONE stitched tree across clusters. Bounded — only
        # as large as tracing is actually sampling.
        from collections import OrderedDict

        self.dup_trace_ctxs: "OrderedDict[int, tuple]" = OrderedDict()
        # primary-side state (parity: primary_context, replica_context.h)
        self._pending_acks: Dict[int, Set[str]] = {}
        self._client_callbacks: Dict[int, Callable[[List[Any]], None]] = {}
        self._learners: Dict[str, int] = {}  # learner -> prepare_start decree
        self._learn_ckpt_dirs: Dict[str, str] = {}  # learner -> frozen ckpt
        # reads/checkpoints gate on this after a promotion (replica.cpp:426)
        self._promotion_watermark = 0
        # follower reads: when this replica last observed itself caught up
        # to the primary's advertised commit point (stamped in _on_prepare
        # and _on_group_check on the SECONDARY side). bounded_stale ops
        # compare `now - _fresh_as_of` against their max_lag_ms bound; a
        # replica that has never synced is infinitely stale by definition
        self._fresh_as_of = float("-inf")
        # lazily hydrated from the .ingested_loads marker (bulk load dedup)
        self._ingested_load_ids: Set[int] = set()
        # decree -> responses computed at idempotent translation time
        # (the logged dup-puts apply as ints; the client wants the
        # original atomic op's response object)
        self._idempotent_responses: Dict[int, List[Any]] = {}
        # the mutation-queue batch: (op_count, callback) spans + the ops
        # accumulated while a 2PC round is in flight
        self._write_queue: List[Tuple[int, Optional[Callable]]] = []
        self._queued_ops: List[WriteOp] = []
        # per-mutation latency tracers (parity: every mutation carries a
        # latency_tracer, replica_2pc.cpp:338-359; slow dumps via
        # dump_trace_points). Write traces share the server's slow log so
        # ONE app-env threshold (replica.slow_query_threshold_ms) governs
        # reads and writes alike
        self._traces: Dict[int, Any] = {}
        # distributed tracing: per-peer prepare hop spans, keyed
        # (decree, peer) — opened at prepare send, closed at ack (the
        # hop whose self-time exposes a lagging secondary)
        self._prepare_spans: Dict[Tuple[int, str], Any] = {}
        self._write_latency = None  # lazy per-table percentile
        self.slow_log = self.server.slow_log
        # node-level write flush window (group_commit.WriteFlushWindow),
        # set by the hosting stub: plog appends stage under its shared
        # flush/fsync and prepare/ack sends aggregate per peer. None =
        # immediate legacy behavior (directly-driven replicas).
        self.plog_sink = None
        # node-level "write" metric entity (stub-provided; None in
        # directly-driven replicas); the queue-depth percentile caches
        # lazily — it sits on the per-write hot path
        self.write_metrics = None
        self._queue_depth_metric = None
        # whether learn checkpoint paths are reachable via the local
        # filesystem (single host / shared fs). Multi-host deployments set
        # False on the stub and checkpoints travel via the file-transfer
        # service (nfs_node.h:84 parity)
        self.shared_fs = True
        self.on_remote_checkpoint: Optional[Callable] = None
        # callbacks to the control plane (meta); tests wire these
        self.on_learn_completed: Optional[Callable[[str], None]] = None
        self.on_replication_error: Optional[Callable[[str, int], None]] = None

    # ---- lifecycle ----------------------------------------------------

    def close(self) -> None:
        self.log.close()
        self.server.close()

    @property
    def ballot(self) -> int:
        return self.config.ballot

    @property
    def last_committed_decree(self) -> int:
        return self.prepare_list.last_committed_decree

    def last_prepared_decree(self) -> int:
        return self.prepare_list.max_decree()

    def ready_to_serve(self) -> bool:
        """Reads/checkpoints allowed only once the promotion-time prepare
        window has re-committed (parity: replica.cpp:426 — the gate that
        keeps a fresh primary from serving state missing acked writes)."""
        return self.last_committed_decree >= self._promotion_watermark

    def staleness_s(self, now: float) -> float:
        """Seconds since this replica last proved itself caught up to the
        primary's advertised commit point. A PRIMARY is fresh by
        definition (it IS the commit point); a secondary's freshness is
        stamped when a prepare/group_check shows it committed everything
        the primary had committed at send time — so the bound is the
        primary→secondary sync cadence, not the mutation rate."""
        if self.status == PartitionStatus.PRIMARY:
            return 0.0
        return max(0.0, now - self._fresh_as_of)

    # ---- config (driven by meta / tests) ------------------------------

    @_serial
    def assign_config(self, config: ReplicaConfig) -> None:
        """Parity: replica_config.cpp ballot-gated role changes."""
        if config.ballot < self.config.ballot:
            return  # stale proposal
        self.config = config
        if config.primary == self.name:
            if self.status != PartitionStatus.PRIMARY:
                self.status = PartitionStatus.PRIMARY
                # serving gate (parity: replica.cpp:426): reads and
                # checkpoints must wait until everything prepared at
                # promotion time has re-committed under the new ballot —
                # an acked write can live in the window as prepared-only
                self._promotion_watermark = self.last_prepared_decree()
                # a new primary must not carry uncommitted decrees from an
                # older window beyond what it can now re-propose; reconcile
                # by re-preparing its own window under the new ballot
                self._reprepare_window()
            else:
                # membership change while primary. First retire learner
                # entries that this config PROMOTES to secondary — they
                # were kept in _learners through the promotion gap so no
                # prepare could miss them, but leaving them forever means
                # a LATER config that removes the node still finds it in
                # _learners and keeps demanding its acks (observed: a
                # shed ex-learner wedging every subsequent write).
                for node in list(self._learners):
                    if node in config.secondaries:
                        del self._learners[node]
                # open decrees stop waiting for ex-members
                members = set(config.secondaries) | set(self._learners)
                for decree in sorted(self._pending_acks):
                    self._pending_acks[decree] &= members
                for decree in sorted(self._pending_acks):
                    if not self._pending_acks[decree]:
                        del self._pending_acks[decree]
                        self._on_decree_ready(decree)
        elif self.name in config.secondaries:
            self.status = PartitionStatus.SECONDARY
            self._clear_primary_state()
        else:
            self.status = PartitionStatus.INACTIVE
            self._clear_primary_state()

    def _clear_primary_state(self) -> None:
        self._pending_acks.clear()
        self._client_callbacks.clear()
        self._traces.clear()
        for psp in self._prepare_spans.values():
            psp.finish()  # hops die with the primaryship; record them
        self._prepare_spans.clear()
        # queued writes die unacked with the primaryship (clients retry)
        self._write_queue.clear()
        self._queued_ops.clear()
        self._idempotent_responses.clear()
        self._learners.clear()
        # learn snapshots for in-flight learners die with the primaryship
        # (each is a full SST copy; completion will never fire to GC them)
        for ckpt in self._learn_ckpt_dirs.values():
            shutil.rmtree(ckpt, ignore_errors=True)
        self._learn_ckpt_dirs.clear()

    def _reprepare_window(self) -> None:
        """New primary: re-send every prepared-but-uncommitted mutation
        under its (new) ballot so the group converges (parity: the
        reconfiguration path re-proposes the open window)."""
        for d in range(self.last_committed_decree + 1,
                       self.last_prepared_decree() + 1):
            mu = self.prepare_list.get_mutation_by_decree(d)
            if mu is None:
                continue
            remu = replace(mu, ballot=self.config.ballot,
                           last_committed=self.last_committed_decree)
            self.prepare_list.prepare(remu)
            self._log_append(remu)
            targets = self._prepare_targets(remu.decree)
            if targets:
                self._pending_acks[remu.decree] = set(targets)

            def _ship(remu=remu, targets=targets) -> None:
                self._send_prepares(remu)
                if not targets:
                    # never leave an empty entry (it would count toward
                    # the pipelining depth forever and wedge the queue)
                    self._on_decree_ready(remu.decree)

            self._after_durable(_ship)

    # ---- group-commit plumbing ----------------------------------------

    def _log_append(self, mu: Mutation) -> None:
        """Plog append through the node's group-commit window when one
        is open (one shared flush/fsync per window); immediate append
        otherwise."""
        sink = self.plog_sink
        if sink is not None:
            sink.append(self.log, mu)
        else:
            self.log.append(mu)

    def _after_durable(self, fn: Callable[[], None]) -> None:
        """Run `fn` only once every mutation staged in the current
        flush window is durable — the ack-after-durable contract under
        group commit. Immediate when no window is open (the append
        already flushed)."""
        sink = self.plog_sink
        if sink is not None:
            sink.after_durable(fn)
        else:
            fn()

    # ---- client write path (primary) ----------------------------------

    # writes queued while a 2PC round is in flight coalesce into ONE
    # following mutation (parity: mutation_queue batching — requests with
    # rpc_request_is_write_allow_batch join the pending mutation,
    # mutation.cpp:390,553; the queue drains when the window moves)
    MAX_BATCH_OPS = 128
    # in-flight 2PC rounds allowed before writes start coalescing (the
    # bounded-staleness pipelining window)
    PIPELINE_DEPTH = 2

    @_serial
    def client_write(self, ops: List[WriteOp],
                     callback: Optional[Callable[[List[Any]], None]] = None
                     ) -> int:
        """Parity: on_client_write -> init_prepare (replica_2pc.cpp:113,328).
        Returns the assigned decree (-1 when queued behind an in-flight
        round), or raises on gate failure."""
        if self.status != PartitionStatus.PRIMARY:
            raise RuntimeError(f"{self.name}: not primary")
        if any(wo.op in ATOMIC_OPS for wo in ops) and len(ops) > 1:
            raise ValueError("atomic ops cannot batch with other writes")
        if self.write_metrics is not None:
            if self._queue_depth_metric is None:
                self._queue_depth_metric = self.write_metrics.percentile(
                    "pipeline_queue_depth")
            self._queue_depth_metric.set(len(self._queued_ops))
        if (self._write_queue
                or len(self._pending_acks) >= self.PIPELINE_DEPTH):
            # the window is at its pipelining depth (or earlier writes
            # already queued — a later write must NOT overtake them, or
            # two puts to one key could apply in reversed order):
            # coalesce batchable writes into the NEXT mutation (bounded
            # staleness, replica_2pc.cpp:366); non-batchable ones and a
            # full batch busy-reject for a client retry
            if (all(wo.op in BATCHABLE_OPS for wo in ops)
                    and sum(n for n, _cb in self._write_queue)
                    + len(ops) <= self.MAX_BATCH_OPS):
                self._write_queue.append((len(ops), callback))
                self._queued_ops.extend(ops)
                return -1
            raise ReplicaBusyError(
                f"{self.name}: write queue busy (retry)")
        decree = self.last_prepared_decree() + 1
        ts = max(int(self.clock() * 1_000_000), self._last_timestamp_us + 1)
        idem_responses = None
        # forced translation (parity: the atomic-idempotent toggle,
        # enable/disable/get_atomic_idempotent): the app-env makes atomic
        # ops ship as concrete puts even without active duplication
        force_idem = (self.server.app_envs.get(
            "replica.atomic_idempotent") == "true")
        if ((self.duplicators or force_idem)
                and any(wo.op in (OP_INCR, OP_CAS, OP_CAM)
                        for wo in ops)):
            # idempotent translation (parity: make_idempotent,
            # replica_2pc.cpp:283 + idempotent_writer.h): a duplicated
            # table must log atomic ops as the CONCRETE puts they
            # resolve to, or the follower would re-execute them. The
            # read-translate is only sound against fully-applied state:
            # an open window could hold a conflicting earlier write, so
            # busy-reject and let the client retry after it drains.
            if self.last_committed_decree != self.last_prepared_decree():
                raise ReplicaBusyError(
                    f"{self.name}: atomic write on a duplicated table "
                    f"must wait for the in-flight window")
            ops, idem_responses = self._make_idempotent(ops, ts)
            # per-item microseconds were handed out above: re-reserve by
            # the OUTPUT count so the next mutation's timetags can't tie
            self._last_timestamp_us = max(self._last_timestamp_us,
                                          ts + max(len(ops), 1) - 1)
        # reserve one microsecond PER OP: duplication stamps op i with
        # ts + i, and the next mutation must not overlap those timetags
        self._last_timestamp_us = ts + max(len(ops), 1) - 1
        from pegasus_tpu.utils import tracing
        from pegasus_tpu.utils.latency_tracer import LatencyTracer

        # the write's own span (child of the carrier RPC's dispatch
        # span): it outlives this call — acks arrive in later dispatches
        # — and closes when the client reply goes out, so the reply send
        # carries this trace's context (and its tail-keep bit) upstream
        wspan = tracing.child_of(
            tracing.current_span(),
            f"2pc.{self.server.app_id}.{self.server.pidx}.d{decree}")
        if wspan is not None:
            self.dup_trace_ctxs[decree] = wspan.ctx()
            while len(self.dup_trace_ctxs) > 1024:
                self.dup_trace_ctxs.popitem(last=False)
        tracer = LatencyTracer(f"write.{self.server.app_id}."
                               f"{self.server.pidx}.d{decree}",
                               span=wspan)
        self._traces[decree] = tracer
        if idem_responses is not None:
            self._idempotent_responses[decree] = idem_responses
        mu = Mutation(
            ballot=self.config.ballot, decree=decree,
            last_committed=self.last_committed_decree,
            timestamp_us=ts, ops=ops)
        # fault site: the PRIMARY's own plog write (parity: the 200-series
        # disk faults hit the primary too — a primary that cannot log must
        # not ack, and must not send prepares it hasn't durably staged)
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(self._fp_primary_plog) is not None:
            self._traces.pop(decree, None)
            self._idempotent_responses.pop(decree, None)
            raise RuntimeError(
                f"{self.name}: primary plog append failed (fault)")
        self.prepare_list.prepare(mu)
        tracer.add_point("prepare_local")
        self._log_append(mu)
        tracer.add_point("append_plog")
        if callback is not None:
            self._client_callbacks[decree] = callback
        targets = self._prepare_targets(decree)
        if targets:
            self._pending_acks[decree] = set(targets)

        # the requesting tenant (bound ambient by the stub's write
        # handler): re-bound around the deferred prepare fan-out so the
        # aggregated 2PC legs keep their tenant tag — the window flush
        # runs them long after this call's binding unwound
        from pegasus_tpu.server import tenancy

        wtenant = tenancy.current()

        def _ship() -> None:
            # runs after the group-commit window hardened the plog (a
            # primary must not send prepares — or ack a zero-member
            # round — before its own log write is durable)
            tracer.add_point("plog_durable")
            with tenancy.bind(wtenant):
                self._send_prepares(mu)
            tracer.add_point("prepares_sent")
            if not targets:
                # no members to wait on: ready now. (Never leave an
                # EMPTY entry in _pending_acks — it would count toward
                # the pipelining depth forever and wedge the queue.)
                self._on_decree_ready(decree)

        self._after_durable(_ship)
        return decree

    def _prepare_targets(self, decree: int) -> List[str]:
        targets = list(self.config.secondaries)
        targets.extend(l for l, start in self._learners.items()
                       if decree >= start)
        return targets

    def _send_prepares(self, mu: Mutation) -> None:
        from pegasus_tpu.utils import tracing

        targets = self._prepare_targets(mu.decree)
        if not targets:
            return  # single-replica: skip the dead encode entirely
        blob = mu.encode()
        tracer = self._traces.get(mu.decree)
        wspan = tracer.span if tracer is not None else None
        for dst in targets:
            psp = None
            if wspan is not None:
                key = (mu.decree, dst)
                psp = self._prepare_spans.get(key)
                if psp is None:
                    # per-peer prepare hop: send -> ack received. Its
                    # SELF time is the wire+peer latency — the span a
                    # lagging secondary shows up in. Re-sends (group
                    # check recovery) extend the same span.
                    psp = tracing.child_of(wspan, f"prepare.{dst}")
                    self._prepare_spans[key] = psp
            with tracing.activate(psp):
                self.transport.send(self.name, dst, "prepare", blob)

    # ---- 2PC message handlers -----------------------------------------

    def on_message(self, src: str, msg_type: str, payload: Any) -> None:
        handler = getattr(self, f"_on_{msg_type}", None)
        if handler is None:
            raise ValueError(f"unknown message type {msg_type}")
        handler(src, payload)

    @_serial
    def _on_prepare(self, src: str, blob: bytes) -> None:
        """Parity: on_prepare (replica_2pc.cpp:532)."""
        mu = Mutation.decode(blob)
        if mu.ballot < self.config.ballot:
            self.transport.send(self.name, src, "prepare_ack", {
                "decree": mu.decree, "ballot": self.config.ballot,
                "err": int(ErrorCode.ERR_INVALID_STATE)})
            return
        if mu.ballot > self.config.ballot:
            # newer configuration exists that we haven't heard about from
            # meta yet; adopt the ballot so older primaries are fenced
            # (reference: the prepare carries the config, replica updates)
            self.config = replace(self.config, ballot=mu.ballot, primary=src)
        if self.status not in (PartitionStatus.SECONDARY,
                               PartitionStatus.POTENTIAL_SECONDARY):
            self.transport.send(self.name, src, "prepare_ack", {
                "decree": mu.decree, "ballot": mu.ballot,
                "err": int(ErrorCode.ERR_INVALID_STATE)})
            return
        if self.status == PartitionStatus.SECONDARY:
            # gap check: a missed prepare (dropped message) leaves a hole a
            # full secondary can never commit across — it must be removed
            # and re-added through the learner flow (PacificA
            # reconfiguration, not voting). A POTENTIAL_SECONDARY is
            # allowed holes: its learn_response fills them.
            for d in range(self.last_committed_decree + 1, mu.decree):
                if self.prepare_list.get_mutation_by_decree(d) is None:
                    self.transport.send(self.name, src, "prepare_ack", {
                        "decree": mu.decree, "ballot": mu.ballot,
                        "err": int(ErrorCode.ERR_INCONSISTENT_STATE)})
                    return
        self.prepare_list.prepare(mu)
        # SAFETY: ack OK only if OUR stored mutation for this decree is the
        # one this primary sent — prepare() keeps a higher-ballot mutation,
        # and acking a discarded prepare would let a deposed primary
        # commit content the group never stored.
        stored = self.prepare_list.get_mutation_by_decree(mu.decree)
        accepted = (stored is not None and stored.ballot == mu.ballot) \
            or mu.decree <= self.last_committed_decree
        if not accepted:
            self.transport.send(self.name, src, "prepare_ack", {
                "decree": mu.decree, "ballot": self.config.ballot,
                "err": int(ErrorCode.ERR_INVALID_STATE)})
            return
        # fail point (parity: the disk-fault injection sites around log
        # writes — the .act 200-series exercise this): a configured
        # write-fault NAKs the prepare like a real aio failure would
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(f"{self.name}::plog_append") is not None:
            self.transport.send(self.name, src, "prepare_ack", {
                "decree": mu.decree, "ballot": self.config.ballot,
                "err": int(ErrorCode.ERR_FILE_OPERATION_FAILED)})
            return
        self._log_append(mu)
        # advance commit point from the piggy-backed primary commit
        mode = (COMMIT_TO_DECREE_HARD
                if self.status == PartitionStatus.SECONDARY
                else COMMIT_TO_DECREE_SOFT)
        self.prepare_list.commit(min(mu.last_committed, mu.decree - 1), mode)
        # follower-read freshness: this prepare proves we now hold every
        # decree the primary had committed when it sent (the piggy-backed
        # last_committed), so stamp the staleness clock
        if (self.status == PartitionStatus.SECONDARY
                and self.last_committed_decree >= mu.last_committed):
            self._fresh_as_of = self.clock()
        # the OK ack waits for the group-commit window's shared
        # flush/fsync: "appended before it can be acked" must mean
        # DURABLY appended, or a crash mid-window could lose a
        # mutation the primary already counted as replicated here
        self._after_durable(lambda: self.transport.send(
            self.name, src, "prepare_ack", {
                "decree": mu.decree, "ballot": mu.ballot,
                "err": int(ErrorCode.ERR_OK)}))

    @_serial
    def _on_prepare_ack(self, src: str, ack: dict) -> None:
        """Parity: on_prepare_reply (replica_2pc.cpp:731)."""
        if self.status != PartitionStatus.PRIMARY:
            return
        decree = ack["decree"]
        if ack["err"] != int(ErrorCode.ERR_OK):
            # a member failed this prepare: PacificA removes it via
            # reconfiguration; surface to the control plane
            if self.on_replication_error is not None:
                self.on_replication_error(src, decree)
            return
        pending = self._pending_acks.get(decree)
        if pending is None:
            return
        pending.discard(src)
        tracer = self._traces.get(decree)
        if tracer is not None:
            tracer.add_point(f"ack.{src}")
        psp = self._prepare_spans.pop((decree, src), None)
        if psp is not None:
            psp.finish()
        if not pending:
            del self._pending_acks[decree]
            self._on_decree_ready(decree)

    def _on_decree_ready(self, decree: int) -> None:
        self.prepare_list.mark_ready(decree)
        self.prepare_list.commit(decree, COMMIT_ALL_READY)
        self._drain_write_queue()

    def _drain_write_queue(self) -> None:
        """The round finished: ship everything queued behind it as ONE
        mutation whose responses split back per original request."""
        if (not self._write_queue or self._pending_acks
                or self.status != PartitionStatus.PRIMARY):
            return
        spans = self._write_queue
        ops = self._queued_ops
        self._write_queue = []
        self._queued_ops = []

        def split_responses(responses: List[Any]) -> None:
            off = 0
            for n, cb in spans:
                if cb is not None:
                    cb(responses[off:off + n])
                off += n

        self.client_write(ops, split_responses)

    def _on_group_check(self, src: str, payload: dict) -> None:
        """Parity: on_group_check (replica_check.cpp:212) — heartbeat from
        the primary carrying its commit point."""
        if payload["ballot"] < self.config.ballot:
            return
        target = min(payload["last_committed"], self.last_prepared_decree())
        if target > self.last_committed_decree:
            self.prepare_list.commit(target, COMMIT_TO_DECREE_HARD)
        # follower-read freshness: caught up to the primary's advertised
        # commit point as of this heartbeat → reset the staleness clock
        if (self.status == PartitionStatus.SECONDARY
                and self.last_committed_decree >= payload["last_committed"]):
            self._fresh_as_of = self.clock()
        self.transport.send(self.name, src, "group_check_ack", {
            "ballot": payload["ballot"],
            "last_committed": self.last_committed_decree})

    def _on_group_check_ack(self, src: str, payload: dict) -> None:
        pass  # liveness bookkeeping arrives with the failure detector

    def broadcast_group_check(self) -> None:
        """Primary heartbeat (parity: group-check timer). Doubles as the
        lost-ack recovery path: any decree still waiting on acks has its
        prepare re-sent to the members that haven't answered (prepare is
        idempotent on the receiver; a re-ack drains the pending set)."""
        if self.status != PartitionStatus.PRIMARY:
            return
        for dst in self.config.secondaries:
            self.transport.send(self.name, dst, "group_check", {
                "ballot": self.config.ballot,
                "last_committed": self.last_committed_decree})
        for decree, pending in sorted(self._pending_acks.items()):
            mu = self.prepare_list.get_mutation_by_decree(decree)
            if mu is None:
                continue
            blob = mu.encode()
            for dst in pending:
                self.transport.send(self.name, dst, "prepare", blob)

    # ---- apply --------------------------------------------------------

    def _apply_mutation(self, mu: Mutation) -> None:
        """Committed mutation -> one engine batch (parity:
        replication_app_base::apply_mutation ->
        on_batched_write_requests)."""
        ws = self.server.write_service
        # deterministic 'now' derived from the primary-assigned timestamp
        now = max(0, mu.timestamp_us // 1_000_000 - PEGASUS_EPOCH_BEGIN)
        ts = mu.timestamp_us
        items: List = []
        responses: List[Any] = []
        # timetags already written EARLIER IN THIS MUTATION per key: a
        # batched dup mutation may touch one key twice, and the engine
        # won't see the first write until apply_items at the end
        dup_floors: Dict[bytes, int] = {}
        cu = self.server.cu  # capacity-unit metering (parity: every
        # write handler feeds capacity_unit_calculator.h:62-104)
        hc = self.server.hotkey_collectors["write"]
        if hc.state.value != "stopped":
            from pegasus_tpu.base.key_schema import restore_key as _rk

            hks = []
            for wo in mu.ops:
                if wo.op in (OP_PUT, OP_REMOVE, OP_DUP_PUT,
                             OP_DUP_REMOVE):
                    hks.append(_rk(wo.request[0])[0])
                elif wo.op in (OP_MULTI_PUT, OP_MULTI_REMOVE):
                    hks.append(wo.request.hash_key)
            hc.capture(hks)
        if len(mu.ops) == 1 and mu.ops[0].op == OP_INGEST:
            # bulk-load ingestion rides alone (ATOMIC_OPS) and takes the
            # write lock only around the engine mutation — its
            # block-service download must not stall the partition
            responses.append(
                self._apply_ingest(mu.ops[0].request, mu.decree))
            callback = self._client_callbacks.pop(mu.decree, None)
            if callback is not None:
                callback(responses)
            return
        # The engine-reading translations (timetags, incr/cas current
        # values) AND the batch apply run under the server's
        # single-writer lock: the env-triggered manual compaction
        # thread takes the same lock (partition_server.manual_compact),
        # and without this exclusion a compaction's overlay reset wipes
        # any mutation applied after its merge snapshot began — acked
        # writes silently lost (found by the combined-chaos drive:
        # sustained load + env compaction on a live onebox).
        from pegasus_tpu.server.capacity_units import units as _cu_units

        with self.server._write_lock:
            # vectorized translate: homogeneous PUT/REMOVE runs go
            # through one run-translate pass (single timetag sweep —
            # byte-identical output) and CU accounting batches into ONE
            # counter touch per mutation instead of one per op (the
            # LUDA observation: per-record write-path work collapses
            # once the records travel in batches, arXiv:2004.03054)
            ok = int(ErrorCode.ERR_OK)
            ops = mu.ops
            n_ops = len(ops)
            cu_total = 0
            i = 0
            while i < n_ops:
                wo = ops[i]
                if wo.op == OP_PUT:
                    j = i + 1
                    while j < n_ops and ops[j].op == OP_PUT:
                        j += 1
                    reqs = [w.request for w in ops[i:j]]
                    cu_total += sum(_cu_units(len(k) + len(ud))
                                    for k, ud, _ets in reqs)
                    items.extend(ws.translate_put_run(reqs, ts))
                    responses.extend([ok] * (j - i))
                    i = j
                    continue
                if wo.op == OP_REMOVE:
                    j = i + 1
                    while j < n_ops and ops[j].op == OP_REMOVE:
                        j += 1
                    keys = [w.request[0] for w in ops[i:j]]
                    cu_total += sum(_cu_units(len(k)) for k in keys)
                    items.extend(ws.translate_remove_run(keys))
                    responses.extend([ok] * (j - i))
                    i = j
                    continue
                if wo.op == OP_MULTI_PUT:
                    cu_total += _cu_units(len(wo.request.hash_key) + sum(
                        len(kv.key) + len(kv.value)
                        for kv in wo.request.kvs))
                    err, its = ws.translate_multi_put(wo.request, ts, now)
                    responses.append(err)
                elif wo.op == OP_MULTI_REMOVE:
                    cu_total += _cu_units(len(wo.request.hash_key) + sum(
                        len(sk) for sk in wo.request.sort_keys))
                    err, count, its = ws.translate_multi_remove(wo.request)
                    responses.append((err, count))
                elif wo.op == OP_INCR:
                    cu_total += _cu_units(len(wo.request.key))
                    resp, its = ws.translate_incr(wo.request, ts, now)
                    resp.decree = mu.decree
                    responses.append(resp)
                elif wo.op == OP_CAS:
                    resp, its = ws.translate_check_and_set(
                        wo.request, ts, now)
                    resp.decree = mu.decree
                    responses.append(resp)
                elif wo.op == OP_CAM:
                    resp, its = ws.translate_check_and_mutate(
                        wo.request, ts, now)
                    resp.decree = mu.decree
                    responses.append(resp)
                elif wo.op == OP_DUP_PUT:
                    key, user_data, expire_ts, timetag = wo.request
                    applied, its = ws.translate_duplicate_put(
                        key, user_data, expire_ts, timetag,
                        dup_floors.get(key, 0))
                    if applied:
                        dup_floors[key] = timetag
                    responses.append(int(applied))
                elif wo.op == OP_DUP_REMOVE:
                    key, timetag = wo.request
                    applied, its = ws.translate_duplicate_remove(
                        key, timetag, dup_floors.get(key, 0))
                    if applied:
                        dup_floors[key] = timetag
                    responses.append(int(applied))
                else:
                    raise ValueError(f"unknown op {wo.op}")
                items.extend(its)
                i += 1
            cu.add_write_units(cu_total)
            sink = self.plog_sink
            if sink is not None and sink.wal_flush_deferred():
                # the engine-WAL frame rides the IO buffer: the ack's
                # durability lives in the private log (hardened before
                # this callback ran), and every decree this WAL could
                # recover replays from the plog anyway — see
                # WriteFlushWindow.wal_flush_deferred
                ws.apply_items(items, mu.decree, wal_flush=False)
            else:
                ws.apply_items(items, mu.decree)
        from pegasus_tpu.utils import tracing

        tracer = self._traces.pop(mu.decree, None)
        wspan = tracer.span if tracer is not None else None
        if wspan is not None:
            # members that never acked (removed mid-round): close their
            # hop spans at apply so the trace is whole
            for key in [k for k in self._prepare_spans
                        if k[0] == mu.decree]:
                self._prepare_spans.pop(key).finish()
        if tracer is not None:
            tracer.add_point("committed_applied")
        callback = self._client_callbacks.pop(mu.decree, None)
        override = self._idempotent_responses.pop(mu.decree, None)
        if callback is not None:
            # the client reply goes out under the write's span so it
            # carries this trace's context — and, when any hop crossed
            # the slow threshold, the tail-keep bit — back upstream
            with tracing.activate(wspan):
                callback(override if override is not None else responses)
        if tracer is not None:
            tracer.add_point("replied")
            from pegasus_tpu.utils import perf_context as perf

            if perf.enabled():
                # the write's cost vector: rows applied and the
                # group-commit wait (append_plog -> plog_durable is
                # exactly the shared-fsync flush-window interval) —
                # rides the slow-log entry and the 2PC span like the
                # read paths' contexts
                pc = perf.PerfContext("write")
                pc.ops = 1
                pc.rows_evaluated = len(mu.ops)
                pc.rows_survived = len(mu.ops)
                stages = dict((s, t) for s, t in tracer.points)
                if "append_plog" in stages and "plog_durable" in stages:
                    pc.queue_wait_ms = max(
                        0.0, (stages["plog_durable"]
                              - stages["append_plog"]) * 1000.0)
                tracer.perf = pc
                if wspan is not None:
                    perf.merge_span_perf(wspan.tags, pc)
            self.slow_log.observe(tracer)
            if self._write_latency is None:
                self._write_latency = self.server.metrics.percentile(
                    "write_latency_ms")
            self._write_latency.set(tracer.total_ms())
        if wspan is not None:
            wspan.finish()

    def has_ingested(self, load_id: int) -> bool:
        """Group-visible ingest dedup: the marker is written by EVERY
        member at apply time, so whoever becomes primary after a failover
        knows the load already committed and will not replicate a second
        OP_INGEST (which could resurrect keys deleted in between)."""
        if load_id in self._ingested_load_ids:
            return True
        marker = os.path.join(self.data_dir, ".ingested_loads")
        if os.path.exists(marker):
            import json as _json

            with open(marker) as f:
                self._ingested_load_ids = set(_json.load(f))
        return load_id in self._ingested_load_ids

    def _record_ingested(self, load_id: int) -> None:
        import json as _json

        self.has_ingested(load_id)  # hydrate from disk first
        self._ingested_load_ids.add(load_id)
        marker = os.path.join(self.data_dir, ".ingested_loads")
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(sorted(self._ingested_load_ids), f)
        os.replace(tmp, marker)

    def _make_idempotent(self, ops: List[WriteOp], ts: int):
        """The (single — atomic ops never batch) atomic op -> the
        concrete dup-tagged puts/removes it resolves to, plus the
        response object to hand the client. Each output op gets ITS OWN
        microsecond (ts + i): two mutates of the same sort key in one
        check_and_mutate must not tie on timetag, or the dup floor would
        silently drop the later one. The caller re-reserves the
        timestamp range by the OUTPUT count."""
        from pegasus_tpu.base.value_schema import (
            extract_user_data,
            generate_timetag,
        )
        from pegasus_tpu.storage.wal import OP_PUT as ITEM_PUT

        ws = self.server.write_service
        now = max(0, ts // 1_000_000 - PEGASUS_EPOCH_BEGIN)
        assert len(ops) == 1, "atomic ops never batch"
        wo = ops[0]
        if wo.op == OP_INCR:
            resp, items = ws.translate_incr(wo.request, ts, now)
        elif wo.op == OP_CAS:
            resp, items = ws.translate_check_and_set(wo.request, ts, now)
        else:
            resp, items = ws.translate_check_and_mutate(wo.request, ts,
                                                        now)
        out_ops: List[WriteOp] = []
        for i, it in enumerate(items):
            if it.op == ITEM_PUT:
                user_data = extract_user_data(ws.data_version, it.value)
                out_ops.append(WriteOp(
                    OP_DUP_PUT,
                    (it.key, user_data, it.expire_ts,
                     generate_timetag(ts + i, ws.cluster_id, False))))
            else:
                out_ops.append(WriteOp(
                    OP_DUP_REMOVE,
                    (it.key,
                     generate_timetag(ts + i, ws.cluster_id, True))))
        # the op may resolve to NO writes (failed check / error): the
        # mutation ships empty and the decree still advances
        return out_ops, [resp]

    def _apply_ingest(self, request, decree: int) -> int:
        """Download this partition's staged SST and ingest it at `decree`."""
        import json as _json
        import tempfile

        from pegasus_tpu.server.bulk_load import (
            BULK_LOAD_FILE,
            BULK_LOAD_INFO,
        )
        from pegasus_tpu.storage.block_service import block_service_for
        from pegasus_tpu.utils.errors import StorageStatus

        root, src_app, load_id = request
        if self.has_ingested(load_id):
            # replayed or duplicated ingest mutation: decree advances,
            # data does not re-apply
            self.server.write_service.apply_items([], decree)
            return int(StorageStatus.OK)
        bs = block_service_for(root)
        info = _json.loads(bs.read_file(f"{src_app}/{BULK_LOAD_INFO}"))
        if info["partition_count"] != self.server.partition_count:
            # still stamp the decree: the mutation is committed groupwide
            # and the watermark must advance identically on every member
            self.server.write_service.apply_items([], decree)
            return int(StorageStatus.INVALID_ARGUMENT)
        remote = f"{src_app}/{self.server.pidx}/{BULK_LOAD_FILE}"
        if not bs.exists(remote):
            with self.server._write_lock:
                self.server.write_service.apply_items([], decree)
            return int(StorageStatus.OK)  # nothing staged for this pidx
        try:
            with tempfile.TemporaryDirectory(prefix="pegingest") as tmp:
                local = os.path.join(tmp, "ingest.sst")
                # the (possibly slow) block-service download runs
                # UNLOCKED; only the engine mutation itself needs the
                # single-writer exclusion (same split as bulk_load.py)
                bs.download(remote, local)
                with self.server._write_lock:
                    self.server.engine.ingest_sst_file(local, decree)
            self._record_ingested(load_id)
        except (OSError, ValueError):
            # staged files must stay immutable+present for the whole load
            # (same contract as the reference). If they vanish mid-apply,
            # STILL stamp the decree — a committed mutation must advance
            # the watermark identically on every member — and surface the
            # failure so meta aborts the load.
            with self.server._write_lock:
                self.server.write_service.apply_items([], decree)
            return int(StorageStatus.IO_ERROR)
        return int(StorageStatus.OK)

    # ---- learning (parity: replica_learn.cpp) -------------------------

    @_serial
    def add_learner(self, learner: str) -> None:
        """Primary: start shipping new prepares to the learner and tell it
        to init_learn (parity: RPC_LEARN_ADD_LEARNER)."""
        if self.status != PartitionStatus.PRIMARY:
            raise RuntimeError("only the primary adds learners")
        self._learners[learner] = self.last_prepared_decree() + 1
        self.transport.send(self.name, learner, "add_learner", {
            "ballot": self.config.ballot,
            "partition_count": self.server.partition_count})

    def _on_add_learner(self, src: str, payload: dict) -> None:
        if payload["ballot"] < self.config.ballot:
            return
        self.status = PartitionStatus.POTENTIAL_SECONDARY
        self.config = replace(self.config, ballot=payload["ballot"],
                              primary=src)
        self.transport.send(self.name, src, "learn_request", {
            "last_committed": self.last_committed_decree})

    def _on_learn_request(self, src: str, payload: dict) -> None:
        """Primary chooses the learn type (parity: on_learn :361)."""
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(f"{self.name}::learn_checkpoint") is not None:
            # checkpoint materialization failed on the learn source: no
            # response — the learner stays POTENTIAL_SECONDARY and the
            # guardian's next add-learner proposal retries the learn
            return
        learner_lc = payload["last_committed"]
        gc_floor = self.server.engine.last_flushed_decree
        if learner_lc >= gc_floor:
            # private log covers the gap -> ship mutations (LT_LOG; the
            # reference's LT_CACHE case folds in: cached mutations are in
            # the log too)
            # ship the whole tail INCLUDING the uncommitted window: the
            # learner must hold every in-flight decree or the first new
            # prepare after its registration point would hit a gap
            mutations = self.log.read_range(learner_lc + 1)
            self.transport.send(self.name, src, "learn_response", {
                "type": LT_LOG,
                "mutations": [mu.encode() for mu in mutations],
                "last_committed": self.last_committed_decree,
            })
        else:
            # gap extends below the log GC floor -> checkpoint copy
            # (LT_APP). Materialize a frozen snapshot via
            # engine.checkpoint() and advertise THAT path — never the live
            # sst dir: a concurrent flush/compaction deletes old L0/L1
            # files mid-copy, so a learner walking the live dir can fail
            # or capture a mixed-generation file set. The reference copies
            # a checkpoint.<decree> dir (replica_learn.cpp:504 +
            # nfs/nfs_node.h:84); the snapshot is GC'd on learn
            # completion/abort.
            ckpt_dir = os.path.join(self.server.engine.data_dir,
                                    f"learn.ckpt.{src}")
            shutil.rmtree(ckpt_dir, ignore_errors=True)
            ckpt_decree = self.server.checkpoint(ckpt_dir)
            self._learn_ckpt_dirs[src] = ckpt_dir
            self.transport.send(self.name, src, "learn_response", {
                "type": LT_APP,
                "checkpoint_dir": ckpt_dir,
                "checkpoint_node": self.name,
                "checkpoint_decree": ckpt_decree,
                "mutations": [mu.encode() for mu in self.log.read_range(
                    ckpt_decree + 1)],
                "last_committed": self.last_committed_decree,
            })

    def _on_learn_response(self, src: str, payload: dict) -> None:
        """Learner applies learned state (parity: on_learn_reply :571,
        on_copy_remote_state_completed :1001). An LT_APP checkpoint on a
        DIFFERENT host (no shared fs) is pulled asynchronously through
        the file-transfer service first — the nfs copy_remote_files leg."""
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(f"{self.name}::learn_apply") is not None:
            # aio failure applying learned state: abort THIS attempt;
            # the replica stays POTENTIAL_SECONDARY and a later
            # add-learner round retries from scratch
            return
        if payload["type"] == LT_APP:
            ckpt = payload["checkpoint_dir"]
            if not (self.shared_fs and os.path.exists(ckpt)):
                if self.on_remote_checkpoint is not None:
                    self.on_remote_checkpoint(src, payload)
                    return  # complete_remote_learn resumes after the copy
                return  # unreachable checkpoint and no transfer: give up
            self._apply_learned_checkpoint(ckpt,
                                           payload["checkpoint_decree"])
        self._finish_learn(src, payload)

    def complete_remote_learn(self, src: str, payload: dict,
                              local_ckpt_dir: str) -> None:
        """File-transfer completion: apply the fetched checkpoint and
        finish the learn exactly like the shared-fs path."""
        self._apply_learned_checkpoint(local_ckpt_dir,
                                       payload["checkpoint_decree"])
        self._finish_learn(src, payload)

    def _finish_learn(self, src: str, payload: dict) -> None:
        for blob in payload["mutations"]:
            mu = Mutation.decode(blob)
            if mu.decree <= self.last_committed_decree:
                continue
            self.prepare_list.prepare(mu)
            self._log_append(mu)
        self.prepare_list.commit(payload["last_committed"],
                                 COMMIT_TO_DECREE_HARD)
        # completion claims the learner HOLDS the tail — wait for the
        # window's shared flush like any other post-append ack
        self._after_durable(lambda: self.transport.send(
            self.name, src, "learn_completion", {}))

    def _apply_learned_checkpoint(self, checkpoint_dir: str,
                                  checkpoint_decree: int) -> None:
        """Replace local storage with the learned checkpoint (parity:
        storage_apply_checkpoint, replication_app_base.h:229)."""
        from pegasus_tpu.storage.engine import StorageEngine

        app_dir = self.server.engine.data_dir
        self.server.engine.close()
        sst_dir = os.path.join(app_dir, "sst")
        shutil.rmtree(sst_dir, ignore_errors=True)
        # decrypt/re-encrypt aware: primary and learner hold different
        # data keys when at-rest encryption is on
        from pegasus_tpu.storage.efile import copy_data_tree
        copy_data_tree(checkpoint_dir, sst_dir)
        wal = os.path.join(app_dir, "wal.log")
        if os.path.exists(wal):
            os.remove(wal)
        self.server.install_engine(StorageEngine(app_dir))
        if self.server.engine.last_committed_decree < checkpoint_decree:
            raise RuntimeError(
                f"learned checkpoint reaches decree "
                f"{self.server.engine.last_committed_decree}, primary "
                f"advertised {checkpoint_decree}")
        self.prepare_list.reset(self.server.engine.last_committed_decree)

    def _on_learn_completion(self, src: str, payload: dict) -> None:
        """Primary: learner caught up; hand to the control plane for the
        config change that upgrades it (parity:
        RPC_LEARN_COMPLETION_NOTIFY -> meta config update)."""
        ckpt = self._learn_ckpt_dirs.pop(src, None)
        if ckpt is not None:
            shutil.rmtree(ckpt, ignore_errors=True)
        if self.on_learn_completed is not None:
            self.on_learn_completed(src)

    # ---- maintenance --------------------------------------------------

    def flush_and_gc_log(self) -> None:
        """Make storage durable, then GC the private log below the durable
        decree — capped by duplication progress: unshipped mutations must
        survive GC or duplication stalls forever (parity: the reference
        holds plog GC back by the dup confirmed decree,
        mutation_log.h:213 + duplication progress plumbing)."""
        from pegasus_tpu.utils.fail_point import fail_point

        if fail_point(f"{self.name}::checkpoint") is not None:
            # a failed checkpoint must leave the WAL un-GC'd: nothing
            # durable moved, so recovery still replays everything
            return
        # PartitionServer.flush carries the single-writer exclusion: a
        # flush swaps the memtable, which must not interleave with the
        # async compaction thread's own overlay reset
        self.server.flush()
        floor = self.server.engine.last_flushed_decree
        for dup in self.duplicators:
            floor = min(floor, dup.confirmed_decree)
        self.log.gc(floor)
