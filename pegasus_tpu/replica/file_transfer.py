"""Remote file transfer: chunked directory copy between nodes.

Parity: src/nfs/ (nfs_node.h:84 copy_remote_files — rDSN-RPC-based bulk
file copy used by LT_APP learning and disk migration; NOT posix NFS).
Message protocol (server side lives on the replica stub):

    "list_dir"         {rid, path}            -> "list_dir_reply"
                       {rid, err, files: [{name, size}]}
    "fetch_chunk"      {rid, path, offset, length}
                       -> "fetch_chunk_reply" {rid, err, data, eof}

Paths are validated against the serving stub's data dirs — a transfer
peer can only read replica state, never arbitrary files.

The client side is an ASYNC session (FileFetchSession): message
handlers cannot block on request/reply (single-threaded dispatch), so
the session advances one outstanding chunk at a time and fires a
completion callback — the same shape as the duplication sessions.
"""

from __future__ import annotations

import itertools
import os

from pegasus_tpu.storage.vfs import logical_size, open_data_file
from typing import Callable, List, Optional, Tuple

CHUNK_SIZE = 1 << 20

_RIDS = itertools.count(5_000_000)


def path_allowed(path: str, roots: List[str]) -> bool:
    real = os.path.realpath(path)
    for root in roots:
        if real == os.path.realpath(root) or real.startswith(
                os.path.realpath(root) + os.sep):
            return True
    return False


class TransferServer:
    """Stub-side handlers (registered by ReplicaStub)."""

    def __init__(self, net, name: str, roots: List[str]) -> None:
        self.net = net
        self.name = name
        self.roots = list(roots)

    def on_list_dir(self, src: str, payload: dict) -> None:
        rid = payload.get("rid")
        path = payload["path"]
        if not path_allowed(path, self.roots) or not os.path.isdir(path):
            self.net.send(self.name, src, "list_dir_reply", {
                "rid": rid, "err": 1, "files": []})
            return
        files = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if os.path.isfile(full):
                files.append({"name": name,
                              "size": logical_size(full)})
        self.net.send(self.name, src, "list_dir_reply", {
            "rid": rid, "err": 0, "files": files})

    def on_fetch_chunk(self, src: str, payload: dict) -> None:
        rid = payload.get("rid")
        path = payload["path"]
        if not path_allowed(path, self.roots) or not os.path.isfile(path):
            self.net.send(self.name, src, "fetch_chunk_reply", {
                "rid": rid, "err": 1, "data": b"", "eof": True})
            return
        with open_data_file(path, "rb") as f:
            f.seek(payload["offset"])
            data = f.read(payload["length"])
            eof = f.tell() >= logical_size(path)
        self.net.send(self.name, src, "fetch_chunk_reply", {
            "rid": rid, "err": 0, "data": data, "eof": eof})


class FileFetchSession:
    """Pulls one remote directory into a local one, chunk by chunk.

    Owner routes "list_dir_reply"/"fetch_chunk_reply" into on_reply();
    `on_done(ok)` fires exactly once at completion or failure.
    """

    def __init__(self, net, name: str, remote_node: str, remote_dir: str,
                 local_dir: str,
                 on_done: Callable[[bool], None]) -> None:
        self.net = net
        self.name = name
        self.remote_node = remote_node
        self.remote_dir = remote_dir
        self.local_dir = local_dir
        self.on_done = on_done
        self._files: List[dict] = []
        self._file_idx = 0
        self._offset = 0
        self._fh = None
        self._rid: Optional[int] = None
        self._finished = False
        os.makedirs(local_dir, exist_ok=True)
        self._send_list()

    # ---- protocol ------------------------------------------------------

    def _send_list(self, reuse_rid: bool = False) -> None:
        if not reuse_rid or self._rid is None:
            self._rid = next(_RIDS)
        self.net.send(self.name, self.remote_node, "list_dir", {
            "rid": self._rid, "path": self.remote_dir})

    def _send_chunk_req(self, reuse_rid: bool = False) -> None:
        if not reuse_rid or self._rid is None:
            self._rid = next(_RIDS)
        f = self._files[self._file_idx]
        self.net.send(self.name, self.remote_node, "fetch_chunk", {
            "rid": self._rid,
            "path": os.path.join(self.remote_dir, f["name"]),
            "offset": self._offset, "length": CHUNK_SIZE})

    def resend(self) -> None:
        """Timer hook: the last request may have been lost. The SAME rid
        is re-sent — minting a new one would invalidate an in-flight
        reply, and a round-trip slower than the tick would then livelock
        (every reply always stale)."""
        if self._finished:
            return
        if self._fh is None and not self._files:
            self._send_list(reuse_rid=True)
        elif self._file_idx < len(self._files):
            self._send_chunk_req(reuse_rid=True)

    def on_reply(self, msg_type: str, payload: dict) -> bool:
        if self._finished or payload.get("rid") != self._rid:
            return False
        if msg_type == "list_dir_reply":
            if payload["err"] != 0:
                self._finish(False)
                return True
            self._files = payload["files"]
            self._file_idx = 0
            self._next_file()
            return True
        if msg_type == "fetch_chunk_reply":
            if payload["err"] != 0:
                self._finish(False)
                return True
            self._fh.write(payload["data"])
            self._offset += len(payload["data"])
            if payload["eof"]:
                self._fh.close()
                self._fh = None
                self._file_idx += 1
                self._next_file()
            else:
                self._send_chunk_req()
            return True
        return False

    def _next_file(self) -> None:
        while self._file_idx < len(self._files):
            f = self._files[self._file_idx]
            if f["size"] == 0:
                open_data_file(os.path.join(self.local_dir, f["name"]), "wb").close()
                self._file_idx += 1
                continue
            self._fh = open_data_file(os.path.join(self.local_dir, f["name"]), "wb")
            self._offset = 0
            self._send_chunk_req()
            return
        self._finish(True)

    def _finish(self, ok: bool) -> None:
        self._finished = True
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self.on_done(ok)
