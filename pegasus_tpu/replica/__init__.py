"""Replication framework: PacificA consensus (reference: src/replica/)."""

from pegasus_tpu.replica.mutation import Mutation, WriteOp
from pegasus_tpu.replica.prepare_list import PrepareList
from pegasus_tpu.replica.mutation_log import MutationLog
from pegasus_tpu.replica.group_commit import WriteFlushWindow
from pegasus_tpu.replica.replica import (
    PartitionStatus,
    Replica,
    ReplicaBusyError,
    ReplicaConfig,
)
