#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): reproduces the tunnel
# transfer measurements that motivated the MaskPrefresher design.
"""Profile the TPU scan path: where do the ~400ms/flush go?

Instruments scan_block_predicate + pallas path with counters/timers and
measures raw tunnel dispatch latency. Not part of the framework; a
diagnostic sidecar for bench tuning.
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

devs = jax.devices()
accel = [d for d in devs if d.platform != "cpu"]
dev = accel[0] if accel else devs[0]
print(f"device: {dev}", flush=True)

# --- raw dispatch latency through the tunnel ---
with jax.default_device(dev):
    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.arange(1024)
    f(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    N = 30
    for _ in range(N):
        f(x).block_until_ready()
    per = (time.perf_counter() - t0) / N * 1000
    print(f"raw jit dispatch round-trip: {per:.2f} ms", flush=True)

    # transfer latency: 1MB up
    big = np.zeros((1 << 20,), dtype=np.uint8)
    t0 = time.perf_counter()
    for _ in range(10):
        jax.device_put(big, dev).block_until_ready()
    print(f"1MB device_put: {(time.perf_counter()-t0)/10*1000:.2f} ms",
          flush=True)
    # download of a small mask
    m = jnp.zeros((2048,), dtype=bool)
    t0 = time.perf_counter()
    for _ in range(30):
        np.asarray(m)
    print(f"2048-bool download: {(time.perf_counter()-t0)/30*1000:.2f} ms",
          flush=True)

# --- instrument the scan path ---
import pegasus_tpu.ops.predicates as preds

orig = preds.scan_block_predicate
stats = {"calls": 0, "time": 0.0, "shapes": {}}


def wrapped(dev_block, now, **kw):
    t0 = time.perf_counter()
    m = orig(dev_block, now, **kw)
    # force completion for honest timing
    np.asarray(m.keep)
    dt = time.perf_counter() - t0
    stats["calls"] += 1
    stats["time"] += dt
    shape = tuple(dev_block.keys.shape)
    s = stats["shapes"].setdefault(shape, [0, 0.0])
    s[0] += 1
    s[1] += dt
    return m


preds.scan_block_predicate = wrapped
import pegasus_tpu.server.scan_coordinator as sc
sc.scan_block_predicate = wrapped
import pegasus_tpu.server.partition_server as psrv
if hasattr(psrv, "scan_block_predicate"):
    psrv.scan_block_predicate = wrapped

sys.argv = ["bench"]
os.environ.setdefault("PEGBENCH_RECORDS", "20000")
import bench

with tempfile.TemporaryDirectory() as td:
    with jax.default_device(dev):
        bc = bench.build_cluster(td, 20000, 64, 7)
        n_hashkeys = max(1, 20000 // 10)
        bc.manual_compact_all()
        bench.run_scans(bc, 60, 64, n_hashkeys, 7, insert_frac=0)
        bench.run_scans(bc, 30, 64, n_hashkeys, 8)
        bc.manual_compact_all()
        bench.run_scans(bc, 300, 64, n_hashkeys, 7, insert_frac=0)
        stats["calls"] = 0
        stats["time"] = 0.0
        stats["shapes"].clear()
        t0 = time.perf_counter()
        ops, recs, secs = bench.run_scans(bc, 300, 64, n_hashkeys, 7)
        print(f"\nmeasured: {ops} ops, {recs} recs in {secs:.2f}s "
              f"-> {ops/secs:.1f} ops/s", flush=True)
        print(f"device predicate calls: {stats['calls']}, "
              f"total {stats['time']*1000:.0f} ms "
              f"({stats['time']/secs*100:.0f}% of wall)", flush=True)
        for shape, (n, t) in sorted(stats["shapes"].items()):
            print(f"  shape {shape}: {n} calls, {t/n*1000:.1f} ms avg",
                  flush=True)
        bc.close()
