#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): bulk-compaction
# throughput at BASELINE scale, reusing the bench's fixture builder
# (bench.build_compact_store) so the synthetic-SST layout lives in ONE
# place. CPU-only by default; PEGPROF_DEVICE=accel places eval on the
# ambient accelerator. PEGPROF_PROFILE=1 wraps the pass in cProfile.
"""`--mesh` is a fast no-accelerator selftest (the compaction twin of
profile_tunnel --watchdog-selftest): over a forced 8-CPU-device mesh it
proves one whole-table dispatch serves every partition's drop masks
byte-identically to the host filter stage, that a wedged watchdog
degrades to host filtering, and exits 0 on PASS — CI-drivable without
hardware."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "--mesh" in sys.argv[1:]:
    # keep the selftest off any real accelerator, and give the mesh its
    # 8 virtual CPU devices BEFORE jax initializes
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import shutil
    import tempfile

    import numpy as np

    from pegasus_tpu.base.value_schema import epoch_now
    from pegasus_tpu.client.client import PegasusClient
    from pegasus_tpu.client.table import Table
    from pegasus_tpu.ops import placement
    from pegasus_tpu.ops.compaction import (
        compaction_eval_drain,
        compaction_eval_submit,
    )
    from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
    from pegasus_tpu.utils.flags import FLAGS

    with tempfile.TemporaryDirectory(prefix="pegmeshcompact") as tmp:
        FLAGS.set("pegasus.storage", "block_codec", "none")
        table = Table(os.path.join(tmp, "t"), partition_count=8)
        c = PegasusClient(table)
        for i in range(1600):
            rc = c.set(b"hk%03d" % (i % 40), b"s%05d" % i,
                       b"v%05d" % i,
                       ttl_seconds=7 if i % 3 == 0 else 0)
            assert rc == 0
        table.flush_all()
        for s in table.partitions.values():
            s.engine.flush()
            s.engine.manual_compact()
        now = epoch_now() + 3600
        placement.mesh_compact_pays = lambda *_a, **_k: True
        for s in table.partitions.values():
            MESH_SERVING.attach(s)
        served = 0
        for pidx, s in sorted(table.partitions.items()):
            lsm = s.engine.lsm
            entries = lsm.bulk_compact_entries()
            masks = MESH_SERVING.try_compact_masks(
                lsm, entries, now, 0, pidx, s.partition_version,
                False, None, want_ets=False, n_windows=1)
            assert masks is not None, f"p{pidx} declined"
            served += 1
            blocks = [((run, i), run.read_block(i), pidx)
                      for run, i, _bm in entries]
            pend = compaction_eval_submit(
                blocks, now, 0, s.partition_version, False,
                operations=None, eval_device=None, want_ets=False)
            host = {tag: drop for tag, drop, _e in
                    compaction_eval_drain(pend, want_ets=False)}
            for run, i, _bm in entries:
                assert np.array_equal(
                    np.asarray(host[(run, i)], bool),
                    np.asarray(masks[(run, i)][0], bool)), \
                    f"p{pidx} block {i} mask mismatch"
        st = MESH_SERVING.status()
        assert st["compact_dispatches"] == 1, st
        assert st["compact_mask_serves"] == 8, st
        # wedged leg: an impossible deadline must decline, not hang
        MESH_SERVING.watchdog.deadline_s = 1e-9
        MESH_SERVING._compact_cache.clear()
        got = MESH_SERVING.try_compact_masks(
            lsm, entries, now + 1, 0, pidx, s.partition_version,
            False, None, want_ets=False, n_windows=1)
        assert got is None, "wedged watchdog still served masks"
        st = MESH_SERVING.status()
        assert st["compact_mesh_fallback_count"] >= 1, st
        MESH_SERVING.reset()
        table.close()
        print(f"mesh compact selftest: PASS (1 dispatch served "
              f"{served}/8 partitions host-identically; wedged "
              f"watchdog declined to host)")
        sys.exit(0)

if os.environ.get("PEGPROF_DEVICE", "cpu") == "cpu":
    from pegasus_tpu.utils.cpu_isolation import force_cpu
    force_cpu()

import bench as B  # noqa: E402

GB = float(os.environ.get("PEGPROF_GB", "1"))
EXPIRED = float(os.environ.get("PEGPROF_EXPIRED", "0.3"))
PARTS = int(os.environ.get("PEGPROF_PARTS", "1"))


def main() -> None:
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    n_records = int(GB * 1e9 / 145)
    with tempfile.TemporaryDirectory(prefix="pegprof",
                                     dir=os.environ.get("PEGPROF_TMP")
                                     ) as tmp:
        t0 = time.perf_counter()
        engines = B.build_compact_store(tmp, n_records, EXPIRED, PARTS, 7)
        size = B._store_bytes(engines)
        print(f"built {n_records} records ({size/1e9:.2f} GB, "
              f"{PARTS} parts) in {time.perf_counter()-t0:.1f}s",
              flush=True)
        pr = None
        if os.environ.get("PEGPROF_PROFILE") == "1":
            import cProfile
            pr = cProfile.Profile()
            pr.enable()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=min(4, PARTS)) as ex:
            for f in [ex.submit(lambda e: e.manual_compact(), e)
                      for e in engines]:
                f.result()
        secs = time.perf_counter() - t0
        if pr is not None:
            import pstats
            pr.disable()
            pstats.Stats(pr).sort_stats("cumulative").print_stats(30)
        size2 = B._store_bytes(engines)
        print(f"compact: {secs:.2f}s -> {size/1e9/secs:.3f} GB/s "
              f"({size/1e9:.2f} GB -> {size2/1e9:.2f} GB)", flush=True)
        for e in engines:
            e.close()


if __name__ == "__main__":
    main()
