#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): bulk-compaction
# throughput at BASELINE scale (>=1 GB), with a phase breakdown, to
# locate the host-side GB/s ceiling. CPU-only by default; run with
# PEGPROF_DEVICE=accel to place eval on the ambient accelerator.
import os
import sys
import time

if os.environ.get("PEGPROF_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pegasus_tpu.base.crc import crc64_batch
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.storage.engine import StorageEngine
from pegasus_tpu.storage.lsm import L1_RUN_CAPACITY
from pegasus_tpu.storage.sstable import SSTableWriter

GB = float(os.environ.get("PEGPROF_GB", "1"))
VALUE = int(os.environ.get("PEGPROF_VALUE", "100"))
BLOCK = 1024


def build(data_dir: str, n_records: int) -> int:
    """Write n_records directly as columnar L1 runs (10% expired)."""
    sst = os.path.join(data_dir, "sst")
    os.makedirs(sst, exist_ok=True)
    now = epoch_now()
    rng = np.random.default_rng(7)
    names = []
    seq = 0
    writer = None
    in_run = 0
    t0 = time.perf_counter()
    meta = {"last_flushed_decree": 1, "data_version": 1}
    total_bytes = 0
    for base in range(0, n_records, BLOCK):
        n = min(BLOCK, n_records - base)
        idx = np.arange(base, base + n)
        hks = idx // 10
        sks = idx % 10
        keys = np.zeros((n, 32), dtype=np.uint8)
        # big-endian u16 hashkey length prefix (12) + "user%08d" + "s%02d"
        keys[:, 1] = 12
        ascii_hk = np.frombuffer(
            b"".join(b"user%08d" % h for h in hks), dtype=np.uint8
        ).reshape(n, 12)
        ascii_sk = np.frombuffer(
            b"".join(b"s%02d" % s for s in sks), dtype=np.uint8
        ).reshape(n, 3)
        keys[:, 2:14] = ascii_hk
        keys[:, 14:17] = ascii_sk
        key_len = np.full(n, 17, dtype=np.int32)
        ets = np.where(rng.random(n) < 0.10, np.uint32(max(1, now - 100)),
                       np.uint32(0)).astype(np.uint32)
        flags = np.zeros(n, dtype=np.uint8)
        offs = (np.arange(n + 1, dtype=np.uint32) * VALUE)
        heap = rng.integers(32, 126, size=n * VALUE,
                            dtype=np.uint8).tobytes()
        hash_lo = (crc64_batch(keys, np.full(n, 12, dtype=np.int64),
                               start=2)
                   & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        if writer is None:
            writer = SSTableWriter(os.path.join(sst, f"l1-{seq}.sst"),
                                   meta=meta)
            seq += 1
        writer.add_block_columnar(keys, key_len, ets, hash_lo, flags,
                                  offs, heap)
        in_run += n
        total_bytes += n * (32 + 4 + 4 + 4 + 1 + 4) + len(heap)
        if in_run >= L1_RUN_CAPACITY:
            writer.finish()
            names.append(os.path.basename(writer.path))
            writer = None
            in_run = 0
    if writer is not None:
        writer.finish()
        names.append(os.path.basename(writer.path))
    import json
    with open(os.path.join(sst, "MANIFEST.json"), "w") as f:
        json.dump({"seq": seq, "l1": names}, f)
    print(f"built {n_records} records (~{total_bytes/1e9:.2f} GB cols) "
          f"in {time.perf_counter()-t0:.1f}s, {len(names)} runs",
          flush=True)
    return total_bytes


def data_bytes(engine) -> int:
    sst = os.path.join(engine.data_dir, "sst")
    return sum(os.path.getsize(os.path.join(sst, n))
               for n in os.listdir(sst) if n.endswith(".sst"))


def main() -> None:
    import tempfile

    n_records = int(GB * 1e9 / (VALUE + 45))
    with tempfile.TemporaryDirectory(prefix="pegprof",
                                     dir=os.environ.get("PEGPROF_TMP")
                                     ) as tmp:
        build(tmp, n_records)
        eng = StorageEngine(tmp)
        assert eng.lsm.bulk_compact_eligible(), "bulk path not eligible"
        size = data_bytes(eng)
        print(f"on-disk: {size/1e9:.2f} GB in "
              f"{len(eng.lsm.bulk_compact_entries())} blocks", flush=True)
        if os.environ.get("PEGPROF_PROFILE") == "1":
            import cProfile
            import pstats
            pr = cProfile.Profile()
            pr.enable()
            t0 = time.perf_counter()
            eng.manual_compact()
            secs = time.perf_counter() - t0
            pr.disable()
            pstats.Stats(pr).sort_stats("cumulative").print_stats(30)
        else:
            t0 = time.perf_counter()
            eng.manual_compact()
            secs = time.perf_counter() - t0
        size2 = data_bytes(eng)
        print(f"compact: {secs:.2f}s -> {size/1e9/secs:.3f} GB/s "
              f"({size/1e9:.2f} GB -> {size2/1e9:.2f} GB)", flush=True)
        eng.close()


if __name__ == "__main__":
    main()
