#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): bulk-compaction
# throughput at BASELINE scale, reusing the bench's fixture builder
# (bench.build_compact_store) so the synthetic-SST layout lives in ONE
# place. CPU-only by default; PEGPROF_DEVICE=accel places eval on the
# ambient accelerator. PEGPROF_PROFILE=1 wraps the pass in cProfile.
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if os.environ.get("PEGPROF_DEVICE", "cpu") == "cpu":
    from pegasus_tpu.utils.cpu_isolation import force_cpu
    force_cpu()

import bench as B  # noqa: E402

GB = float(os.environ.get("PEGPROF_GB", "1"))
EXPIRED = float(os.environ.get("PEGPROF_EXPIRED", "0.3"))
PARTS = int(os.environ.get("PEGPROF_PARTS", "1"))


def main() -> None:
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    n_records = int(GB * 1e9 / 145)
    with tempfile.TemporaryDirectory(prefix="pegprof",
                                     dir=os.environ.get("PEGPROF_TMP")
                                     ) as tmp:
        t0 = time.perf_counter()
        engines = B.build_compact_store(tmp, n_records, EXPIRED, PARTS, 7)
        size = B._store_bytes(engines)
        print(f"built {n_records} records ({size/1e9:.2f} GB, "
              f"{PARTS} parts) in {time.perf_counter()-t0:.1f}s",
              flush=True)
        pr = None
        if os.environ.get("PEGPROF_PROFILE") == "1":
            import cProfile
            pr = cProfile.Profile()
            pr.enable()
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=min(4, PARTS)) as ex:
            for f in [ex.submit(lambda e: e.manual_compact(), e)
                      for e in engines]:
                f.result()
        secs = time.perf_counter() - t0
        if pr is not None:
            import pstats
            pr.disable()
            pstats.Stats(pr).sort_stats("cumulative").print_stats(30)
        size2 = B._store_bytes(engines)
        print(f"compact: {secs:.2f}s -> {size/1e9/secs:.3f} GB/s "
              f"({size/1e9:.2f} GB -> {size2/1e9:.2f} GB)", flush=True)
        for e in engines:
            e.close()


if __name__ == "__main__":
    main()
